"""Host-side block-store parameter plane + gradient-drop straggler mitigation.

Reference (UNVERIFIED, SURVEY.md §0):
``.../bigdl/parameters/AllReduceParameter.scala`` — gradient/weight partition
exchange over Spark BlockManager blocks — and
``.../bigdl/optim/DistriOptimizer.scala`` — the ``dropPercentage`` /
``computeThresholdbatchSize`` / ``warmupIterationNum`` straggler gradient-drop
(SURVEY §5.3: "iteration proceeds after (1-p)*N partitions' gradients arrive;
late gradients discarded; thresholds computed over a warmup window").

TPU-native placement of the capability: INSIDE a pod slice the gradient
exchange is XLA collectives over ICI (``parallel/all_reduce.py``) — one
compiled SPMD program cannot partially complete, so there is nothing to
drop there (the round-1/2 analysis stands). ACROSS processes/slices — the
DCN boundary, where real-world TPU stragglers actually live (host jitter,
NIC contention, preemption blips) — this module re-creates the reference's
BlockManager dataflow verbatim on a host-side block store:

* ``put_gradients``      — each process splits its locally-reduced gradient
  into ``n_procs`` partitions and publishes the remote slices, keyed by
  ``(iteration, partition, source)`` exactly like the reference's
  deterministic ``BlockId``;
* ``aggregate_my_partition`` — the partition owner polls for contributions
  and, after the warmup window has calibrated arrival times, stops waiting
  at the calibrated deadline once ``1 - drop_percentage`` of contributions
  arrived; late gradients are DISCARDED and the mean is taken over what
  arrived (the reference's drop semantics);
* ``publish_weights`` / ``get_weights`` — the owner updates its weight
  partition and publishes it; everyone assembles the full vector.

Two store backends: the JAX **coordination service** KV store (the same
service ``jax.distributed`` bootstraps on — no extra infrastructure on a
pod, rides DCN) and a **shared filesystem** directory (atomic renames).
The reference's FP16 compression maps to bf16/fp16 casts on the encoded
slices.

Honest scope notes (measured in ``benchmarks/blockstore_bench.py``; also
docs/parallelism.md):

* partition ownership is static, so a straggling *owner's compute* still
  bounds the publish of its own weight partition — a COMPUTE straggler
  stalls both this plane and sync SPMD equally;
* a *transfer* straggler (slow gradient puts — the reference's slow
  BlockManager fetch) is the drop's win domain, and reaping it requires
  ``async_puts``: with synchronous puts the slow transfers sit in front
  of the straggler's own weight publish and the get_weights barrier eats
  the whole delay anyway (drop fires, zero wall-clock saved — measured);
  ``DistriOptimizer`` enables async_puts whenever a drop policy is set;
* the per-contribution calibration quantile needs the FAST cluster to
  hold at least ``1 - drop_percentage`` of the sample mass, i.e. pods of
  n >= 3 for one straggler — at n=2 every remote sample IS the straggler
  and the deadline chases its delay (measured; harmless, just no win).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("bigdl_tpu")

_MAGIC = b"BDBS"


def encode_array(arr: np.ndarray) -> bytes:
    """Self-describing little header + raw bytes (C-order). Extension
    dtypes whose ``dtype.str`` is an opaque void code (ml_dtypes bfloat16
    et al.) are recorded by NAME so decode can resolve them."""
    arr = np.asarray(arr)
    shape = arr.shape  # before ascontiguousarray: it promotes 0-d to 1-d
    arr = np.ascontiguousarray(arr)
    dtype_code = arr.dtype.str
    if dtype_code.lstrip("<>|=").startswith("V"):
        dtype_code = arr.dtype.name  # e.g. "bfloat16"
    dt = dtype_code.encode()
    head = _MAGIC + struct.pack("<B", len(dt)) + dt
    head += struct.pack("<B", len(shape)) + b"".join(
        struct.pack("<q", s) for s in shape)
    return head + arr.tobytes()


def decode_array(blob: bytes) -> np.ndarray:
    if blob[:4] != _MAGIC:
        raise ValueError("not a block-store array blob")
    off = 4
    (ndt,) = struct.unpack_from("<B", blob, off)
    off += 1
    code = blob[off:off + ndt].decode()
    try:
        dt = np.dtype(code)
    except TypeError:
        import ml_dtypes

        dt = np.dtype(getattr(ml_dtypes, code))
    off += ndt
    (nsh,) = struct.unpack_from("<B", blob, off)
    off += 1
    shape = struct.unpack_from(f"<{nsh}q", blob, off) if nsh else ()
    off += 8 * nsh
    return np.frombuffer(blob[off:], dtype=dt).reshape(shape).copy()


class BlockStore:
    """Abstract immutable-once-put block store (the BlockManager analog)."""

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def try_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def contains(self, key: str) -> bool:
        """Existence probe WITHOUT fetching the value. The fallback
        fetches-and-discards (correct everywhere); backends override
        where a metadata check is cheaper — polling loops (e.g. the
        serving handoff's ``pending()``) call this per tick, and a
        fallback read would move the full payload just to answer a
        boolean."""
        return self.try_get(key) is not None

    def get_blocking(self, key: str, timeout_s: float,
                     poll_s: float = 0.002) -> bytes:
        deadline = time.monotonic() + timeout_s
        while True:
            v = self.try_get(key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"block {key!r} not published within {timeout_s}s — a "
                    "peer process likely died (bounded retry will restart "
                    "from checkpoint)")
            time.sleep(poll_s)


class MemBlockStore(BlockStore):
    """In-process dict backend: the cheapest store for single-process
    tests and the in-process disaggregated-serving transfer
    (``serving/disagg.py``). Thread-safe (one lock) so a producer
    thread and the main loop can share it; it is NOT visible across
    processes — use :class:`FsBlockStore` or
    :class:`CoordServiceBlockStore` for that."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._blocks: Dict[str, bytes] = {}

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._blocks[key] = bytes(value)

    def try_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._blocks.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._blocks.pop(key, None)

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._blocks


class FsBlockStore(BlockStore):
    """Shared-directory backend; atomic via write-temp + os.rename."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "__"))

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(value)
        os.rename(tmp, path)

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def contains(self, key: str) -> bool:
        return os.path.exists(self._path(key))


class CoordServiceBlockStore(BlockStore):
    """Backend over the JAX coordination-service KV store — the service
    ``jax.distributed.initialize`` already runs, so a pod gets the exchange
    plane for free over DCN (no Spark/BlockManager infrastructure)."""

    def __init__(self, prefix: str = "bigdl_bs") -> None:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "CoordServiceBlockStore needs jax.distributed.initialize() "
                "(Engine.init_distributed) to have run first")
        self._client = client
        self._prefix = prefix
        self._self_check()

    def _self_check(self) -> None:
        """Pin the error-wording contract against the LIVE client at
        startup: the busy-poll and overwrite-retry paths classify the
        client's human-readable status text, so a jaxlib that rewords
        its missing-key/key-exists errors must fail HERE, loudly, not on
        the first training iteration's poll. The probe key is
        DETERMINISTIC per rank — no cross-rank race (containerized ranks
        share PIDs but not process_index), and a crash between put and
        delete is reclaimed by the next attempt's delete-first — while
        staying unique across live ranks."""
        try:
            import jax

            rank = jax.process_index()
        except Exception:
            rank = os.getpid()
        probe = f"selfcheck/{rank}"

        class _ProbeFailed(RuntimeError):
            """Probe semantics broken (delete/put did not take effect) —
            distinct from the wording-classification failure below, which
            points the operator at _classify_status's token lists."""

        try:
            self.delete(probe)                      # reclaim crashed probe
            if self.try_get(probe) is not None:     # 'missing' classified
                raise _ProbeFailed(
                    "CoordServiceBlockStore self-check failed — probe key "
                    "still visible after delete: this client's deletes do "
                    "not take effect (NOT a _classify_status wording issue)")
            self.put(probe, b"x")
            self.put(probe, b"y")                   # 'exists' -> del+retry
            if self.try_get(probe) != b"y":
                raise _ProbeFailed(
                    "CoordServiceBlockStore self-check failed — overwrite-"
                    "retry did not land: delete+put on an existing key left "
                    "a stale value (NOT a _classify_status wording issue)")
            self.delete(probe)
        except _ProbeFailed:
            raise
        except Exception as e:
            raise RuntimeError(
                "CoordServiceBlockStore self-check failed — this jaxlib's "
                "coordination-service error wording is not recognized by "
                "_classify_status (update its token lists): "
                f"{e!r}") from e

    def _k(self, key: str) -> str:
        return f"{self._prefix}/{key}"

    # The coordination client surfaces gRPC statuses as generic exceptions
    # whose MESSAGE carries the code — classify on that, so only the two
    # expected statuses (key exists / key missing) are absorbed and a
    # genuinely broken client (auth failure, shutdown, serialization)
    # raises instead of degrading into a silent busy-poll that ends in a
    # misleading "peer process likely died" timeout. Missing-key wordings
    # are checked FIRST so "does not exist" can never classify as exists.
    @staticmethod
    def _classify_status(exc: BaseException) -> str:
        """'missing' | 'exists' | 'other'."""
        msg = str(exc).upper().replace(" ", "_").replace("-", "_")
        if any(t in msg for t in ("NOT_FOUND", "NOTFOUND",
                                  "DOES_NOT_EXIST", "DOESN'T_EXIST",
                                  "NO_SUCH_KEY", "MISSING_KEY")):
            return "missing"
        if any(t in msg for t in ("ALREADY_EXISTS", "KEY_EXISTS",
                                  "DUPLICATE_KEY")):
            return "exists"
        return "other"

    def put(self, key: str, value: bytes) -> None:
        try:
            self._client.key_value_set_bytes(self._k(key), value)
        except Exception as e:
            # the coordination KV refuses overwrites — delete + retry.
            # Every hot-path key is iteration-unique (and the per-step
            # pos marker deletes-then-puts explicitly), so this only
            # fires on rare retry collisions
            if self._classify_status(e) != "exists":
                logger.error("coordination KV put(%s) failed: %s", key, e)
                raise
            self.delete(key)
            self._client.key_value_set_bytes(self._k(key), value)

    def try_get(self, key: str) -> Optional[bytes]:
        try:
            return self._client.key_value_try_get_bytes(self._k(key))
        except Exception as e:
            if self._classify_status(e) != "missing":
                logger.error("coordination KV get(%s) failed: %s", key, e)
                raise
            return None

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(self._k(key))
        except Exception as e:
            if self._classify_status(e) != "missing":
                logger.error("coordination KV delete(%s) failed: %s",
                             key, e)
                raise


def default_block_store() -> BlockStore:
    """Coordination-service store when a jax.distributed client exists,
    else a local FsBlockStore (single-process / tests). Only the expected
    no-client RuntimeError falls back — a genuinely broken coordination
    client must surface, not silently degrade a pod to per-process local
    stores that deadlock."""
    try:
        return CoordServiceBlockStore()
    except RuntimeError:
        root = os.environ.get(
            "BIGDL_BLOCKSTORE_DIR",
            os.path.join(os.path.abspath("."), ".bigdl_blockstore"))
        return FsBlockStore(root)


class GradientDropPolicy:
    """The reference's straggler thresholds (``setDropModuleProperty``):
    no drops during the first ``warmup_iteration`` iterations; PER-
    CONTRIBUTION arrival durations (the reference computed its threshold
    over per-task compute times, one sample per model per iteration) from
    the last ``compute_threshold_batch_size`` samples calibrate the
    deadline at the ``1 - drop_percentage`` quantile — so a minority
    straggler (mass < p) is persistently dropped while the quantile stays
    in the fast cluster, and a RECOVERED straggler re-enters as soon as
    its arrivals (observed late via :meth:`BlockStoreParameter.
    _probe_late_arrivals`) pull the quantile back over its times.
    ``max_drop_percentage`` caps how many contributions one aggregation may
    discard regardless of the deadline."""

    def __init__(self, drop_percentage: float,
                 max_drop_percentage: Optional[float] = None,
                 compute_threshold_batch_size: int = 100,
                 warmup_iteration: int = 20,
                 min_deadline_s: float = 0.05) -> None:
        if not 0.0 <= drop_percentage < 1.0:
            raise ValueError("drop_percentage must be in [0, 1)")
        self.min_deadline_s = float(min_deadline_s)
        self.drop_percentage = float(drop_percentage)
        self.max_drop_percentage = (
            drop_percentage if max_drop_percentage is None
            else float(max_drop_percentage))
        if self.max_drop_percentage < self.drop_percentage:
            raise ValueError(
                "max_drop_percentage must be >= drop_percentage")
        self.warmup_iteration = int(warmup_iteration)
        self._samples: deque = deque(maxlen=int(compute_threshold_batch_size))

    def record(self, duration_s: float) -> None:
        self._samples.append(float(duration_s))

    def deadline(self, iteration: int) -> Optional[float]:
        """Seconds an aggregation may wait before dropping; None = no drop
        allowed yet (warmup, or no calibration samples)."""
        if iteration < self.warmup_iteration or not self._samples:
            return None
        q = 1.0 - self.drop_percentage
        quant = float(np.quantile(np.asarray(self._samples), min(q, 1.0)))
        # floor guards against sub-ms calibration windows dropping honest
        # contributions on scheduler jitter (engineering knob, no reference
        # counterpart — BlockManager fetches were never sub-ms)
        return max(quant, self.min_deadline_s)

    def min_arrivals(self, n_contributors: int) -> int:
        """Contributions an owner must have before the deadline can fire
        (self always counts): ceil((1 - max_drop) * n)."""
        need = int(np.ceil((1.0 - self.max_drop_percentage) * n_contributors))
        return max(1, need)


class BlockStoreParameter:
    """The AllReduceParameter dataflow over a host block store, partitioned
    by PROCESS (the reference partitioned by executor). Pure numpy + store:
    process identity is explicit, so the logic is unit-testable with
    threads sharing one FsBlockStore — no pod required.

    Per iteration ``t`` (driver calls in this order):

        put_gradients(t, flat_grad)          # publish remote slices
        g, n, dropped = aggregate_my_partition(t)
        ... owner optimizer update on its weight slice ...
        publish_weights(t + 1, new_wshard)
        flat_w = get_weights(t + 1)          # assemble the full vector
    """

    def __init__(self, store: BlockStore, n_procs: int, pid: int,
                 total_size: int, compress: Optional[str] = None,
                 drop_policy: Optional[GradientDropPolicy] = None,
                 namespace: str = "arp",
                 timeout_s: Optional[float] = None,
                 async_puts: bool = False) -> None:
        self.store = store
        self.n = int(n_procs)
        self.pid = int(pid)
        if not 0 <= self.pid < self.n:
            raise ValueError(f"pid {pid} outside 0..{n_procs - 1}")
        self.total_size = int(total_size)
        self.padded_size = ((self.total_size + self.n - 1) // self.n) * self.n
        self.shard_size = self.padded_size // self.n
        if compress not in (None, "bf16", "fp16"):
            raise ValueError(f"unknown compress {compress!r}")
        self.compress = compress
        self.drop = drop_policy
        self.ns = namespace
        self.timeout_s = timeout_s if timeout_s is not None else float(
            os.environ.get("BIGDL_BLOCKSTORE_TIMEOUT_S", "300"))
        self.dropped_total = 0          # contributions discarded so far
        # per-source drop counts + (iteration, dropped pids) log — the
        # drop-targeting diagnostics the width tests assert on (only the
        # actual straggler should ever appear here)
        self.dropped_by_src: Dict[int, int] = {}
        self.drop_log: List[Tuple[int, Tuple[int, ...]]] = []
        self._my_slice_cache: Optional[np.ndarray] = None
        # (iteration, src) -> that iteration's aggregation start time, for
        # contributions dropped at the deadline whose blocks have not
        # arrived yet — the next aggregations probe them so a late
        # arrival's true (upper-bound) duration can enter the calibration
        # window and the deadline can adapt upward on recovery
        self._late_probes: Dict[Tuple[int, int], float] = {}
        # per-peer window of recent RAW publish→arrival wall-clock deltas
        # (time.time() - send_ts). The minimum over the window estimates
        # that peer's constant clock-offset + minimum-transfer baseline;
        # calibration records only the EXCESS over it, so NTP skew of
        # either sign cannot inflate (or deflate) the drop deadline. A
        # bounded window lets the baseline track slow clock drift.
        self._peer_transfer_raw: Dict[int, deque] = {}
        # async_puts decouples this process's REMOTE gradient transfers
        # from its own aggregate→publish_weights pipeline (the reference
        # decoupled them structurally: gradient tasks vs BlockManager
        # hosts). Without it a slow-transfer straggler delays its own
        # weight publish and the get_weights barrier eats the whole
        # delay, making gradient-drop wall-clock-neutral — measured in
        # benchmarks/blockstore_bench.py
        self.async_puts = bool(async_puts)
        self._put_thread: Optional[threading.Thread] = None
        self._put_error: Optional[BaseException] = None

    # -- keys (deterministic BlockId analog) -------------------------------

    def _gkey(self, t: int, part: int, src: int) -> str:
        return f"{self.ns}/g/{t}/{part}/{src}"

    def _wkey(self, t: int, part: int) -> str:
        return f"{self.ns}/w/{t}/{part}"

    def _skey(self, t: int, name: str, src: int) -> str:
        return f"{self.ns}/s/{t}/{name}/{src}"

    # -- slices ------------------------------------------------------------

    def _pad(self, flat: np.ndarray) -> np.ndarray:
        flat = np.asarray(flat, dtype=np.float32).ravel()
        if flat.size != self.total_size:
            raise ValueError(
                f"flat vector has {flat.size} elements, expected "
                f"{self.total_size}")
        if self.padded_size != flat.size:
            flat = np.concatenate(
                [flat, np.zeros(self.padded_size - flat.size, np.float32)])
        return flat

    def _slice(self, flat_padded: np.ndarray, part: int) -> np.ndarray:
        return flat_padded[part * self.shard_size:(part + 1) * self.shard_size]

    def _encode(self, arr: np.ndarray) -> bytes:
        if self.compress == "bf16":
            import ml_dtypes

            arr = arr.astype(ml_dtypes.bfloat16)
        elif self.compress == "fp16":
            arr = arr.astype(np.float16)
        return encode_array(arr)

    @staticmethod
    def _decode(blob: bytes) -> np.ndarray:
        return decode_array(blob).astype(np.float32)

    # Gradient blobs carry an 8-byte wall-clock send marker so the OWNER
    # can fold the contribution's publish→arrival duration into its
    # calibration sample (max with wait-since-aggregation-start — see
    # aggregate_my_partition): without it, an owner that is itself the
    # slowest process records ~0 s for contributions that landed before it
    # began aggregating, collapsing the window to min_deadline_s and
    # dropping honest peers on the first jitter. Wall clock (not
    # monotonic) because the marker crosses processes; the owner is
    # skew-immune either way — it subtracts a per-peer baseline (min of
    # recent raw deltas, see _transfer_sample) before recording, so a
    # CONSTANT clock offset of either sign cancels and only excess
    # transfer/queue delay enters the calibration window.
    def _encode_g(self, arr: np.ndarray) -> bytes:
        return struct.pack(">d", time.time()) + self._encode(arr)

    @staticmethod
    def _decode_g(blob: bytes) -> Tuple[float, np.ndarray]:
        (send_ts,) = struct.unpack(">d", blob[:8])
        return send_ts, BlockStoreParameter._decode(blob[8:])

    def _transfer_sample(self, src: int, send_ts: float) -> float:
        """Skew-bounded publish→arrival calibration term for a gradient
        block from ``src``: the raw wall-clock delta minus that peer's
        baseline — the min over its PREVIOUS raw deltas, which estimates
        clock offset plus best-case transfer time. From the second marker
        on, a constant NTP offset of either sign cancels and only excess
        transfer/queue delay is recorded; without the baseline, positive
        skew (owner clock ahead of sender) inflated EVERY sample and
        permanently disabled straggler drops (ADVICE r5). The peer's
        FIRST marker has no baseline and records its raw delta — one
        possibly-skewed sample cannot outlive the bounded calibration
        window (and typically lands during warmup), while a genuinely
        early-published blob's sitting time stays visible to the
        calibration (the round-4 slow-owner fix).

        Tradeoff (inherent — one-directional timestamps cannot separate
        a constant clock offset from a constant sitting time): an owner
        that is persistently ~S s slower than its peers now calibrates
        toward the VARIATION in sitting time rather than S itself, so
        once the window fills, a hiccup larger than the deadline costs
        one dropped contribution before :meth:`_probe_late_arrivals`
        records the late arrival's full wait and pulls the quantile back
        up. That one-drop-then-adapt cost buys skew immunity; the
        pre-baseline behavior was strictly worse under skew (drops
        permanently disabled)."""
        raw = time.time() - send_ts
        window = self._peer_transfer_raw.setdefault(src, deque(maxlen=32))
        baseline = min(window) if window else 0.0
        window.append(raw)
        return max(0.0, raw - baseline)

    # -- the four reference verbs -----------------------------------------

    def put_gradients(self, t: int, flat_grad: np.ndarray) -> None:
        """Reference ``putGradients``: publish this process's gradient
        slice for every REMOTE partition; the local slice stays in memory.
        Also records this process's position marker so a retry-from-
        checkpoint can sweep its stale blocks (see ``sweep_stale``)."""
        flat = self._pad(flat_grad)
        self._my_slice_cache = self._slice(flat, self.pid).copy()
        # the position marker is the one NON-iteration-unique key (same
        # key every step) — delete-then-put explicitly, instead of riding
        # put()'s exists-message heuristic on the overwrite-refusing
        # coordination KV every single iteration
        self.store.delete(f"{self.ns}/pos/{self.pid}")
        self.store.put(f"{self.ns}/pos/{self.pid}",
                       encode_array(np.int64(t)))
        blobs = [(self._gkey(t, part, self.pid),
                  self._encode_g(self._slice(flat, part)))
                 for part in range(self.n) if part != self.pid]

        def _send():
            try:
                for key, blob in blobs:
                    self.store.put(key, blob)
            except BaseException as e:  # surfaced on the next join
                self._put_error = e

        if self.async_puts:
            self._join_puts()           # at most ONE outstanding transfer
            self._put_thread = threading.Thread(target=_send, daemon=True)
            self._put_thread.start()
        else:
            _send()
            if self._put_error is not None:
                e, self._put_error = self._put_error, None
                raise e

    def _join_puts(self) -> None:
        """Wait for the previous iteration's async transfer and surface
        any error it hit (a broken store must fail the training loop, not
        vanish into a daemon thread)."""
        if self._put_thread is not None:
            self._put_thread.join()
            self._put_thread = None
        if self._put_error is not None:
            e, self._put_error = self._put_error, None
            if isinstance(e, Exception):
                raise e
            # a stored KeyboardInterrupt/SystemExit from the SENDER thread
            # is a dead transfer, not a live interrupt of THIS thread —
            # surface it as a regular error so callers' except Exception
            # guards treat it uniformly
            raise RuntimeError(
                f"async gradient put thread died with {e!r}") from e

    def sweep_stale(self, aux_names: Sequence[str] = ()) -> None:
        """Delete every block THIS process may have left in the store by a
        previous attempt (bounded by its recorded position marker) — run
        before re-entering the training loop after a retry-from-checkpoint,
        where the iteration counter restarts and same-numbered stale blocks
        would otherwise alias fresh ones. Peers resynchronize through their
        own timeout→retry→sweep cycle (pod-wide failures — the common case,
        and the one the pod retry test exercises — sweep everywhere at
        once)."""
        self._join_puts()       # a retried attempt's transfer may be live
        blob = self.store.try_get(f"{self.ns}/pos/{self.pid}")
        if blob is None:
            return
        last_t = int(decode_array(blob))
        for t in range(max(0, last_t - 2), last_t + 2):
            for part in range(self.n):
                if part != self.pid:
                    self.store.delete(self._gkey(t, part, self.pid))
            self.store.delete(self._wkey(t, self.pid))
            for name in aux_names:
                self.store.delete(self._skey(t, name, self.pid))
        self.store.delete(f"{self.ns}/pos/{self.pid}")

    def aggregate_my_partition(
            self, t: int) -> Tuple[np.ndarray, int, List[int]]:
        """Reference ``aggregateGradientPartition`` + gradient-drop: poll
        remote contributions for MY partition; once past warmup, stop at
        the calibrated deadline if enough arrived. Returns (mean gradient
        over arrived contributions, n_arrived, dropped source pids)."""
        if self._my_slice_cache is None:
            raise RuntimeError("put_gradients must run first each iteration")
        self._probe_late_arrivals(t)
        # GC any contribution a straggler published AFTER iteration t-2's
        # post-aggregation delete (the weight-fetch barrier keeps processes
        # within one iteration of each other, so t-2 blocks are dead)
        for src in range(self.n):
            if src != self.pid:
                self.store.delete(self._gkey(t - 2, self.pid, src))
        acc = self._my_slice_cache.astype(np.float64)
        self._my_slice_cache = None
        pending = [s for s in range(self.n) if s != self.pid]
        arrived = 1
        t0 = time.monotonic()
        deadline = self.drop.deadline(t) if self.drop is not None else None
        min_needed = (self.drop.min_arrivals(self.n)
                      if self.drop is not None else self.n)
        hard_deadline = t0 + self.timeout_s
        while pending:
            for src in list(pending):
                blob = self.store.try_get(self._gkey(t, self.pid, src))
                if blob is not None:
                    send_ts, contrib = self._decode_g(blob)
                    acc += contrib
                    arrived += 1
                    pending.remove(src)
                    if self.drop is not None:
                        # PER-CONTRIBUTION sample = max(wait since MY
                        # aggregation start, baseline-corrected
                        # publish→arrival from the sender's embedded
                        # marker — see _transfer_sample). The wait term is
                        # the actual decision variable (the deadline cuts
                        # off wait-since-start), so compute-slow peers keep
                        # registering their full lateness and the quantile
                        # can adapt upward; the transfer term keeps an
                        # owner that is ITSELF the slowest from recording
                        # ~0 s for contributions that landed before it
                        # began aggregating — per-peer VARIATION in
                        # sitting time (a constant component cancels into
                        # the skew baseline; see _transfer_sample's
                        # tradeoff note). A deadline-truncated wait is
                        # still never recorded (in-loop arrivals have
                        # wait < deadline by construction), so the window
                        # cannot fill with deadline-valued samples.
                        self.drop.record(max(
                            0.0, time.monotonic() - t0,
                            self._transfer_sample(src, send_ts)))
            if not pending:
                break
            now = time.monotonic()
            if (deadline is not None and now - t0 >= deadline
                    and arrived >= min_needed):
                break  # drop the late ones (reference semantics)
            if now > hard_deadline:
                raise TimeoutError(
                    f"partition {self.pid}: only {arrived}/{self.n} gradient "
                    f"contributions after {self.timeout_s}s at iteration {t} "
                    "— a peer process likely died")
            time.sleep(0.002)
        if pending:
            self.dropped_total += len(pending)
            self.drop_log.append((t, tuple(pending)))
            for src in pending:
                self.dropped_by_src[src] = self.dropped_by_src.get(src, 0) + 1
                self._late_probes[(t, src)] = t0
            logger.warning(
                "iteration %d partition %d: dropped %d straggler gradient "
                "contribution(s) from %s (%d/%d arrived)",
                t, self.pid, len(pending), pending, arrived, self.n)
        # cleanup this iteration's arrived blocks for my partition; a
        # DROPPED source's block is left for _probe_late_arrivals (its
        # eventual arrival is the calibration signal) and is GC'd at t+2
        for src in range(self.n):
            if src != self.pid and (t, src) not in self._late_probes:
                self.store.delete(self._gkey(t, self.pid, src))
        return (acc / arrived).astype(np.float32), arrived, pending

    def _probe_late_arrivals(self, t: int) -> None:
        """Check whether contributions dropped by earlier aggregations have
        landed since; record the observed (upper-bound) arrival duration so
        the calibrated deadline can adapt UPWARD when a straggler recovers.
        Probes whose blocks never appear by GC time (t-2) are discarded
        without a sample — a dead peer must not inflate the window."""
        if self.drop is None or not self._late_probes:
            return
        for (tp, src), t0 in list(self._late_probes.items()):
            blob = self.store.try_get(self._gkey(tp, self.pid, src))
            if blob is not None:
                # same max(wait, baseline-corrected transfer) convention
                # as the in-loop sample: the wait term (observed from the
                # DROPPED iteration's aggregation start) is what lets a
                # recovered compute-slow straggler pull the quantile back
                # up. Only the 8-byte marker is needed — skip the array
                # decode.
                (send_ts,) = struct.unpack(">d", blob[:8])
                self.drop.record(max(0.0, time.monotonic() - t0,
                                     self._transfer_sample(src, send_ts)))
                del self._late_probes[(tp, src)]
                self.store.delete(self._gkey(tp, self.pid, src))
            elif tp <= t - 2:
                del self._late_probes[(tp, src)]

    def publish_weights(self, t: int, wshard: np.ndarray) -> None:
        """Reference ``sendWeightPartition``; also GCs this owner's weight
        block from two iterations ago (every peer has long fetched it —
        the aggregate/fetch barriers keep processes within one iteration)."""
        wshard = np.asarray(wshard, np.float32).ravel()
        if wshard.size != self.shard_size:
            raise ValueError(
                f"weight shard has {wshard.size} elements, expected "
                f"{self.shard_size}")
        self.store.put(self._wkey(t, self.pid), encode_array(wshard))
        self.store.delete(self._wkey(t - 2, self.pid))

    def get_weights(self, t: int) -> np.ndarray:
        """Reference ``getWeights``: fetch every owner's weight partition
        (blocking — weight partitions are never dropped) and assemble the
        full unpadded fp32 vector."""
        out = np.empty(self.padded_size, np.float32)
        for part in range(self.n):
            blob = self.store.get_blocking(self._wkey(t, part), self.timeout_s)
            out[part * self.shard_size:(part + 1) * self.shard_size] = \
                decode_array(blob)
        return out[:self.total_size]

    # -- small scalar/array side-channel (loss, BN state, grad norms) ------

    def publish_aux(self, t: int, name: str, value: np.ndarray) -> None:
        self.store.put(self._skey(t, name, self.pid),
                       encode_array(np.asarray(value)))
        self.store.delete(self._skey(t - 2, name, self.pid))

    def gather_aux(self, t: int, name: str,
                   blocking: bool = True) -> Dict[int, np.ndarray]:
        """All processes' published values for ``name`` at iteration t.
        Blocking mode waits for every process (used where the value is
        required for correctness, e.g. global grad-norm partials)."""
        out: Dict[int, np.ndarray] = {}
        for src in range(self.n):
            key = self._skey(t, name, src)
            if blocking:
                out[src] = decode_array(
                    self.store.get_blocking(key, self.timeout_s))
            else:
                blob = self.store.try_get(key)
                if blob is not None:
                    out[src] = decode_array(blob)
        return out
