"""Expert parallelism: mixture-of-experts with all_to_all dispatch.

No reference counterpart (SURVEY.md §2.4: no MoE layers in the reference;
expert parallel listed as out-of-scope for parity — built here as a
first-class TPU extension). The design is the GShard/Switch dense-dispatch
formulation, which is the shape XLA maps best onto TPU:

* gating, top-k selection and capacity masking are dense einsums over a
  ``(tokens, experts, capacity)`` one-hot dispatch/combine tensor — no
  gather/scatter, so everything tiles onto the MXU;
* expert placement is ``lax.all_to_all`` over the mesh axis: tokens routed
  to expert e travel to the chip owning e, the expert MLPs run as one
  batched (vmapped) matmul per chip, and a second all_to_all brings results
  home — both transfers ride ICI.

Pure functions usable inside any ``shard_map``; capacity drops follow the
standard cumsum-position rule (tokens beyond an expert's capacity contribute
zero, matching Switch Transformer semantics).
"""

from __future__ import annotations

from typing import Callable, Optional


def top_k_gating(logits, k: int, capacity: int):
    """Build dispatch/combine tensors from router logits.

    ``logits``: (T, E). Returns ``(dispatch, combine)`` of shape
    (T, E, C): ``dispatch`` is the 0/1 routing tensor, ``combine`` carries
    the gate probabilities on the same support. Top-k per token, positions
    within each expert assigned in token order, overflow dropped.
    """
    import jax
    import jax.numpy as jnp

    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    # top-k expert mask per token, built iteratively (k is small and static)
    masked = probs
    sel = []
    for _ in range(k):
        ix = jnp.argmax(masked, axis=-1)                     # (T,)
        onehot = jax.nn.one_hot(ix, E, dtype=probs.dtype)    # (T, E)
        sel.append(onehot)
        masked = masked * (1.0 - onehot)
    dispatch_e = jnp.zeros_like(probs)
    for onehot in sel:
        dispatch_e = dispatch_e + onehot                      # (T, E) 0/1
    # position of each token within its expert's queue (token order)
    pos = jnp.cumsum(dispatch_e, axis=0) - dispatch_e         # (T, E)
    keep = dispatch_e * (pos < capacity)
    pos_onehot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=probs.dtype)   # (T,E,C)
    dispatch = keep[..., None] * pos_onehot                   # (T, E, C)
    gates = probs * keep
    # renormalize the surviving top-k gates per token (Switch/GShard rule)
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    combine = (gates / denom)[..., None] * pos_onehot
    return dispatch, combine


def moe_layer(x, router_w, expert_params, expert_fn: Callable,
              axis_name: str = "expert", top_k: int = 1,
              capacity_factor: float = 1.25,
              capacity: Optional[int] = None):
    """Expert-parallel MoE block, called inside shard_map over ``axis_name``.

    * ``x`` — this chip's token shard ``(T_local, d)``.
    * ``router_w`` — replicated router weights ``(d, E)`` over ALL experts.
    * ``expert_params`` — THIS chip's experts' parameters, each leaf with a
      ``(E_local, ...)`` leading axis (host side: shard the ``(E, ...)``
      stack with ``in_specs=P(axis_name)``).
    * ``expert_fn(params_one_expert, tokens) -> tokens`` — the expert net.

    Returns ``(T_local, d)`` combined outputs for this chip's tokens.
    """
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    n_dev = lax.psum(1, axis_name)
    T, d = x.shape
    E = router_w.shape[1]
    assert E % n_dev == 0, f"{E} experts over {n_dev} chips"
    e_local = E // n_dev
    if capacity is None:
        capacity = max(1, int(capacity_factor * top_k * T / E))

    logits = jnp.matmul(x, router_w)                          # (T, E)
    dispatch, combine = top_k_gating(logits, top_k, capacity)

    # route: (T,E,C)×(T,d) → (E,C,d), then all_to_all so chip j receives
    # every chip's slabs for ITS experts
    slabs = jnp.einsum("tec,td->ecd", dispatch, x)            # (E, C, d)
    slabs = slabs.reshape(n_dev, e_local, capacity, d)
    slabs = lax.all_to_all(slabs, axis_name, split_axis=0, concat_axis=0,
                           tiled=False)                        # (n_dev, e_loc, C, d)
    # merge the senders' capacity slots: expert e now sees n_dev*C tokens
    slabs = slabs.transpose(1, 0, 2, 3).reshape(e_local, n_dev * capacity, d)

    out = jax.vmap(expert_fn)(expert_params, slabs)           # (e_loc, n_dev*C, d)

    # inverse route
    out = out.reshape(e_local, n_dev, capacity, d).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                          # (n_dev, e_loc, C, d)
    out = out.reshape(E, capacity, d)
    return jnp.einsum("tec,ecd->td", combine, out)            # (T_local, d)


def mlp_expert(params, tokens):
    """Default expert net: GELU MLP. ``params = {"w1": (d, h), "b1": (h,),
    "w2": (h, d), "b2": (d,)}`` (one expert's slice, no leading E axis)."""
    import jax
    import jax.numpy as jnp

    h = jax.nn.gelu(jnp.matmul(tokens, params["w1"]) + params["b1"])
    return jnp.matmul(h, params["w2"]) + params["b2"]
