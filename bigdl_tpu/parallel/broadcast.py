"""ModelBroadcast — place a model's parameters across a mesh.

Reference (UNVERIFIED, SURVEY.md §0): ``.../models/utils/ModelBroadcast.scala``
— broadcasts the model once per job with weights DETACHED
(``getAndClearWeightBias``) so the big arrays ride the Spark broadcast
efficiently and are re-attached per executor clone.

TPU-native: the "broadcast" is a sharding decision, not a wire protocol —
``jax.device_put`` with a replicated (or partitioned) ``NamedSharding``
hands XLA the placement, and ICI moves the bytes once. The detach/attach
dance disappears: params are already a separate pytree from the module
(SURVEY.md §7 design stance). Kept as a class for reference-shaped call
sites.
"""

from __future__ import annotations

from typing import Optional


class ModelBroadcast:
    """``ModelBroadcast().broadcast(mesh, model)`` → params placed on every
    chip (replicated), returned as the device pytree; ``value()`` retrieves
    it (reference API shape)."""

    def __init__(self) -> None:
        self._params = None
        self._model = None

    def broadcast(self, mesh, model):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        model._materialize_params()
        sharding = NamedSharding(mesh, P())  # replicate over every mesh axis
        self._params = jax.device_put(model.params, sharding)
        self._model = model
        return self

    def value(self):
        """The placed params pytree (reference ``value()`` returns the
        executor-local model; our model is the module + these params)."""
        assert self._params is not None, "broadcast() first"
        return self._params

    def model(self):
        return self._model
