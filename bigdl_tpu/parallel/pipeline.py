"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

No reference counterpart (SURVEY.md §2.4: no stage partitioning anywhere in
the reference's ``optim/``; pipeline parallel is the documented TPU-native
extension). Design follows the standard TPU pipelining recipe: every chip
holds one stage's parameters; activations hop to the next stage with
``lax.ppermute`` (one nearest-neighbour ICI transfer per tick) while
microbatches stream through, filling and draining the pipeline.

The whole schedule is ONE traced ``lax.fori_loop`` inside ``shard_map`` —
XLA sees a static program with ``n_micro + n_stages - 1`` ticks, each tick a
(stage-compute, ppermute) pair it can overlap. Autodiff works end-to-end:
the transpose of ``ppermute`` is the reverse permute, so ``jax.grad``
produces the backward pipeline automatically (bubbles and all) with no
hand-written schedule.

Homogeneous-stage form: ``fn(stage_params, x) -> y`` with matching x/y
shapes (classic transformer-block pipelining). Heterogeneous models should
pad stages to a common signature or pipeline only their uniform trunk.
"""

from __future__ import annotations

from typing import Callable


def gpipe(fn: Callable, stage_params, microbatches, axis_name: str = "pipe"):
    """Run ``microbatches`` through a ``n_stages``-deep pipeline.

    Call inside a ``shard_map`` over ``axis_name``:

    * ``stage_params`` — the stacked per-stage pytree: each leaf
      ``(n_stages, ...)`` (see :func:`stack_stage_params`), passed through
      shard_map with ``in_specs=P(axis_name)`` so each chip holds a unit
      slice; ``gpipe`` strips that unit leading axis itself.
    * ``microbatches`` — ``(M, mb, ...)`` the full microbatched input,
      replicated (only stage 0 reads it).

    Returns ``(M, mb, ...)`` outputs, replicated on every chip (the last
    stage's results are psum-broadcast so downstream loss code is
    placement-oblivious).
    """
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    n_stages = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    stage_params = jax.tree_util.tree_map(lambda l: l[0], stage_params)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]

    perm = [(i, i + 1) for i in range(n_stages - 1)]
    out_dtype = jax.eval_shape(
        lambda p, x: fn(p, x), stage_params, microbatches[0]
    ).dtype

    def tick(t, carry):
        recv, outputs = carry
        # stage 0 ingests microbatch t (clamped; masked out when t >= M)
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), keepdims=False
        )
        x = jnp.where(idx == 0, feed, recv)
        y = fn(stage_params, x)
        # last stage completes microbatch t - (n_stages - 1)
        done = t - (n_stages - 1)
        write = jnp.logical_and(idx == n_stages - 1,
                                jnp.logical_and(done >= 0, done < M))
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y, lax.dynamic_index_in_dim(
                outputs, jnp.maximum(done, 0), keepdims=False)),
            jnp.maximum(done, 0), 0,
        )
        recv = lax.ppermute(y, axis_name, perm)
        return recv, outputs

    # carries are device-varying (each chip holds different in-flight data);
    # mark the initial zeros as such for shard_map's replication typing.
    # Deriving them FROM the input (×0) also inherits whatever OTHER mesh
    # axes the microbatches vary over (e.g. 'data' on a composed
    # DP×TP×PP mesh) — fresh zeros would type as replicated there and the
    # fori_loop carry would mismatch its body.
    from bigdl_tpu.utils.compat import device_varying_marker

    vary = device_varying_marker(axis_name)
    recv0 = vary((microbatches[0] * 0).astype(out_dtype))
    out0 = vary((microbatches * 0).astype(out_dtype))
    _, outputs = lax.fori_loop(0, M + n_stages - 1, tick, (recv0, out0))
    # replicate the last stage's outputs to every chip
    outputs = lax.psum(
        jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def stack_stage_params(per_stage_params):
    """Host helper: list of per-stage pytrees (same structure) → one pytree
    with a ``(n_stages, ...)`` leading axis per leaf, ready for
    ``in_specs=P(axis_name)``."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves]),
        *per_stage_params,
    )


def microbatch(x, n_micro: int):
    """Host helper: (B, ...) → (n_micro, B/n_micro, ...)."""
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible by {n_micro} microbatches"
    return x.reshape(n_micro, B // n_micro, *x.shape[1:])
