"""Tensor (model) parallelism: Megatron-style column/row-parallel layers.

No reference counterpart (SURVEY.md §2.4 parallelism inventory: the
reference's only distributed strategy is data parallelism — tensor/model
parallel is listed as the natural TPU extension via param sharding). This
module supplies that extension as first-class primitives designed for the
TPU interconnect:

* **Column-parallel linear** — weight ``(out, in)`` sharded on ``out``
  across the mesh axis. Each chip computes its output-feature slice with a
  full copy of the activations; no communication on the forward pass
  (optionally an ``all_gather`` to rematerialize the full output). The
  backward pass ``psum``s the activation gradient — XLA emits the collective
  from the transpose of the replication, nothing hand-written.
* **Row-parallel linear** — weight sharded on ``in``; activations arrive
  feature-sharded (e.g. from a column-parallel predecessor), each chip
  computes a partial product and one ``psum`` over ICI completes the sum.
* **tp_mlp** — the canonical Megatron block: column-parallel expansion →
  nonlinearity → row-parallel projection, exactly one collective (the
  closing psum) per block.
* **tp_attention** — multi-head attention with heads sharded across the
  axis: column-parallel QKV, local attention per head group, row-parallel
  output projection.

All functions are pure and run inside a ``shard_map`` over the TP mesh axis;
``split_*`` helpers produce the host-side sharded views for ``in_specs``.
Tested on the 8-virtual-device CPU mesh (SURVEY.md §4 pattern).

The SERVING plane consumes these primitives too: the KV-cached
decode/prefill steps (``models/transformer.py``, ``mesh=`` on
``make_batch_decode_step``/``make_batch_prefill_step``) thread
:func:`row_parallel_linear` through the attention-output and fc2
projections under ``utils.compat.shard_map`` — column-parallel QKV/fc1
arrive pre-sliced via ``tp_param_specs``'s in_specs, so each block costs
exactly the two closing psums, with the per-layer K/V cache sharded on
its head axis (``bigdl_tpu.serving.sharded``). Use ``compat.shard_map``
(not ``jax.shard_map``) around these functions when the code must run on
jax 0.4.x as well.
"""

from __future__ import annotations

import math
from typing import Optional


def column_parallel_linear(x, w_shard, b_shard=None, axis_name: str = "model",
                           gather_output: bool = False):
    """y_local = x @ w_shard.T (+ b_shard).

    ``x``: replicated activations ``(..., in)``; ``w_shard``: this chip's
    output-row slice ``(out/n, in)``; returns ``(..., out/n)`` — or the full
    ``(..., out)`` when ``gather_output`` (one all_gather). Note the gathered
    value is still device-varying to shard_map's replication checker; prefer
    the ungathered form with ``out_specs`` carrying the feature axis, or pass
    ``check_vma=False`` to shard_map when gathering.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    y = jnp.matmul(x, w_shard.T)
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        y = lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, w_shard, b=None, axis_name: str = "model",
                        accum_dtype=None, partial_add=None):
    """y = psum_over_axis(x_shard @ w_shard.T) (+ b).

    ``x_shard``: feature-sharded activations ``(..., in/n)``; ``w_shard``:
    this chip's input-column slice ``(out, in/n)``. The single ``psum`` is
    the block's only collective; the bias is added once (post-psum).

    ``accum_dtype`` (e.g. ``jnp.float32``) carries each chip's partial
    product AND the psum in that dtype, rounding to ``x_shard.dtype``
    once after the reduction — without it, low-precision activations
    (bf16 serving) round per chip and again per psum addend, so the
    sharded result drifts a full low-precision ulp from the unsharded
    matmul (enough to flip a greedy argmax on near-tied logits; the
    serving plane's TP steps pass fp32 here for exactly that reason).

    ``partial_add`` (requires ``accum_dtype``): an extra per-chip partial
    contribution in the accumulation dtype, folded into the SAME closing
    psum — the serving plane's per-row LoRA delta rides here, so adapted
    projections keep the one-collective-per-projection budget (an
    all-zeros partial passes through exactly: ``acc + 0.0 == acc``).
    """
    import jax.lax as lax
    import jax.numpy as jnp

    if accum_dtype is not None:
        acc = lax.dot_general(
            x_shard, w_shard,
            (((x_shard.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=accum_dtype)
        if partial_add is not None:
            acc = acc + partial_add.astype(accum_dtype)
        y = lax.psum(acc, axis_name).astype(x_shard.dtype)
    else:
        if partial_add is not None:
            raise ValueError("partial_add requires accum_dtype")
        y = lax.psum(jnp.matmul(x_shard, w_shard.T), axis_name)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w1_shard, b1_shard, w2_shard, b2, axis_name: str = "model",
           activation=None):
    """Megatron MLP block: column-parallel W1 → act → row-parallel W2.

    ``w1_shard``: ``(hidden/n, in)``, ``w2_shard``: ``(out, hidden/n)``.
    The intermediate stays sharded on hidden features — no collective until
    the closing psum in the row-parallel projection.
    """
    import jax.nn

    act = activation or jax.nn.gelu
    h = column_parallel_linear(x, w1_shard, b1_shard, axis_name)
    return row_parallel_linear(act(h), w2_shard, b2, axis_name)


def tp_attention(x, wq, wk, wv, wo, axis_name: str, n_heads_local: int,
                 causal: bool = False, bo=None):
    """Head-sharded multi-head self-attention.

    ``x``: replicated ``(B, T, d_model)``. ``wq/wk/wv``: column-parallel
    shards ``(d_local, d_model)`` where ``d_local = n_heads_local * head_dim``;
    ``wo``: row-parallel shard ``(d_model, d_local)``. ``n_heads_local`` is
    required (``total_heads / tp_size``) — defaulting it would silently merge
    a chip's heads into one. Each chip attends over its own head group (zero
    communication), then one psum closes the output projection — the standard
    Megatron attention layout mapped onto ICI.
    """
    from bigdl_tpu.parallel.ring_attention import attention

    q = column_parallel_linear(x, wq, axis_name=axis_name)
    k = column_parallel_linear(x, wk, axis_name=axis_name)
    v = column_parallel_linear(x, wv, axis_name=axis_name)
    B, T, d_local = q.shape
    h = n_heads_local
    hd = d_local // h
    q = q.reshape(B, T, h, hd)
    k = k.reshape(B, T, h, hd)
    v = v.reshape(B, T, h, hd)
    o = attention(q, k, v, causal=causal,
                  scale=1.0 / math.sqrt(hd)).reshape(B, T, d_local)
    return row_parallel_linear(o, wo, bo, axis_name)


# There are deliberately no host-side weight-splitting helpers: pass the
# UNSPLIT weights through shard_map and let in_specs do the sharding —
# ``P(axis, None)`` for column-parallel (output rows), ``P(None, axis)`` for
# row-parallel (input columns). shard_map hands each chip exactly the slice
# these functions expect.
