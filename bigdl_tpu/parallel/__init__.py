"""bigdl_tpu.parallel — the distributed parameter/communication plane
(reference layer L7, SURVEY.md §2.4 / §5.8)."""

from bigdl_tpu.parallel.all_reduce import AllReduceParameter, flatten_params
from bigdl_tpu.parallel.ring_attention import (
    attention, ring_attention, ulysses_attention,
)

__all__ = [
    "AllReduceParameter", "flatten_params",
    "attention", "ring_attention", "ulysses_attention",
]
