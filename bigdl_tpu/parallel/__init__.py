"""bigdl_tpu.parallel — the distributed parameter/communication plane
(reference layer L7, SURVEY.md §2.4 / §5.8) plus the TPU-native tensor/
pipeline/sequence/expert parallel extensions the reference lacks."""

from bigdl_tpu.parallel.all_reduce import AllReduceParameter, flatten_params
from bigdl_tpu.parallel.block_store import (
    BlockStore, BlockStoreParameter, CoordServiceBlockStore, FsBlockStore,
    GradientDropPolicy, default_block_store,
)
from bigdl_tpu.parallel.broadcast import ModelBroadcast
from bigdl_tpu.parallel.moe import mlp_expert, moe_layer, top_k_gating
from bigdl_tpu.parallel.pipeline import gpipe, microbatch, stack_stage_params
from bigdl_tpu.parallel.ring_attention import (
    attention, ring_attention, stripe_sequence, striped_ring_attention,
    ulysses_attention, unstripe_sequence,
)
from bigdl_tpu.parallel.tensor_parallel import (
    column_parallel_linear, row_parallel_linear, tp_attention, tp_mlp,
)

__all__ = [
    "AllReduceParameter", "flatten_params", "ModelBroadcast",
    "BlockStore", "BlockStoreParameter", "CoordServiceBlockStore",
    "FsBlockStore", "GradientDropPolicy", "default_block_store",
    "attention", "ring_attention", "stripe_sequence",
    "striped_ring_attention", "ulysses_attention", "unstripe_sequence",
    "column_parallel_linear", "row_parallel_linear", "tp_mlp", "tp_attention",
    "gpipe", "microbatch", "stack_stage_params",
    "moe_layer", "top_k_gating", "mlp_expert",
]
