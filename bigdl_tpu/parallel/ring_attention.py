"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

No reference counterpart (SURVEY.md §5.7: the reference predates attention;
its only sequence handling is a serial ``Recurrent`` loop). These are the
framework's first-class long-context primitives, designed for the TPU
interconnect:

* **Ring attention** (blockwise, online-softmax): each chip holds one
  sequence shard of Q/K/V; K/V blocks rotate around the ring with
  ``lax.ppermute`` (nearest-neighbour ICI hops) while each chip accumulates
  its Q-block's attention with the streaming max/sum rescaling — full
  attention over N·T tokens with T-sized memory per chip and no all-gather.
* **Ulysses attention** (all-to-all): ``lax.all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention per head
  group, and re-shards back — cheaper for moderate sequence lengths when
  heads ≥ chips.

Both are pure functions usable inside any ``shard_map`` over a mesh axis
(tested on the 8-virtual-device CPU mesh exactly like the DP plane).
"""

from __future__ import annotations

import math
from typing import Optional


def _local_attention(q, k, v, scale: float, causal: bool,
                     q_offset=0, k_offset=0):
    """Dense softmax attention on local blocks.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D); offsets give the blocks' global
    positions for causal masking across sequence shards.
    """
    import jax.numpy as jnp

    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return out / jnp.maximum(p.sum(-1)[..., None].swapaxes(1, 2), 1e-20)


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Single-device multi-head attention, (B, T, H, D) layout."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _local_attention(q, k, v, scale, causal)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, use_flash: bool = False):
    """Blockwise ring attention inside a ``shard_map`` over ``axis_name``.

    q/k/v: this chip's sequence shard, (B, T_local, H, D); the global
    sequence is the concatenation over the mesh axis in axis-index order.
    Returns the (B, T_local, H, D) attention output for the local Q block.

    ``use_flash=True`` computes each K/V block with the Pallas flash kernel
    and merges blocks by their log-sum-exp — NEITHER direction materializes
    a (T, T) score block, so T_local can grow to the kernel's O(T) memory
    limit. Causal mode runs the diagonal block through the causal kernel
    and nulls future-originated blocks via their LSE (striped-causal ring).
    The backward is a flash-block ring too: per-block FlashAttention-2
    gradients against the saved global log-sum-exp, with dk/dv accumulators
    travelling around the ring back to their block's home rank.
    """
    if use_flash:
        sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
        return _get_ring_flash()(q, k, v, axis_name, float(sc), bool(causal),
                                 "contiguous")
    return _ring_einsum(q, k, v, axis_name, causal, scale)


def _ring_step_spec(schedule: str, causal: bool):
    """Per-step block policy shared by BOTH flash-ring schedules; returns
    ``spec(step, src, my) -> (causal_flag, causal_offset, keep_pred)``:

    * ``contiguous``: step 0 is the diagonal block (causal kernel); later
      rotations run non-causal and, in causal mode, blocks from this
      chip's future are nulled via ``keep_pred`` (-inf LSE / zero grads).
    * ``striped``: EVERY rotation runs the causal kernel — inclusive
      diagonal for stripes from earlier ranks, strict (offset -1) for
      later ones — so no block is computed then discarded.
    """
    if schedule == "striped":
        def spec(step, src, my):
            import jax.numpy as jnp

            return True, jnp.where(src <= my, 0, -1), None
    else:
        def spec(step, src, my):
            if step == 0:
                return causal, None, None
            return False, None, (src < my) if causal else None
    return spec


def _ring_flash_impl(q, k, v, axis_name: str, scale: float, spec):
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.ops.flash_attention import flash_attention_with_lse

    from bigdl_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # the ring length is static — a Python unroll keeps exactly one pallas
    # lowering shape per (causal-variant) call site (a traced fori_loop
    # mixing kernel variants trips jax's closed-call lowering cache)
    m = l = o_acc = None
    kb, vb = k, v
    for step in range(n):
        src = (my - step) % n
        causal_s, off, keep = spec(step, src, my)
        o_i, lse_i = flash_attention_with_lse(q, kb, vb, scale,
                                              causal=causal_s,
                                              causal_offset=off)
        if keep is not None:
            lse_i = jnp.where(keep, lse_i, jnp.full_like(lse_i, -jnp.inf))
        if step == 0:
            m, l = lse_i, jnp.ones_like(lse_i)
            o_acc = o_i.astype(jnp.float32)
        else:
            m_new = jnp.maximum(m, lse_i)
            corr = jnp.exp(m - m_new)      # rescale old accumulators
            w = jnp.exp(lse_i - m_new)     # this block's weight
            o_acc = (o_acc * corr.transpose(0, 2, 1)[..., None]
                     + o_i.astype(jnp.float32)
                     * w.transpose(0, 2, 1)[..., None])
            l = l * corr + w
            m = m_new
        if step < n - 1:                   # last rotation would be dead
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    l_safe = jnp.maximum(l, 1e-20)
    out = (o_acc / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    return out, m + jnp.log(l_safe)                 # lse_global (B, H, T)


def _ring_flash_bwd_impl(q, k, v, o, lse, do, axis_name: str, scale: float,
                         spec):
    """Flash-block ring backward: O(T_local) memory like the forward.

    dq accumulates locally; dk/dv accumulators TRAVEL with their K/V block
    around the ring (n total rotations bring them home). Each block pair's
    gradients are computed against the GLOBAL lse, so the per-block
    contributions sum exactly — no recomputation of the (T, T) scores.
    """
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.ops.flash_attention import flash_attention_block_grads

    from bigdl_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq = dk_acc = dv_acc = None
    kb, vb = k, v
    for step in range(n):
        src = (my - step) % n
        causal_s, off, keep = spec(step, src, my)
        dq_i, dk_i, dv_i = flash_attention_block_grads(
            q, kb, vb, o, lse, do, scale, causal=causal_s,
            causal_offset=off)
        if keep is not None:
            # excluded blocks never entered the global softmax, so their
            # p = exp(s − lse_global) is unbounded (can overflow to inf):
            # null with a NaN-safe select, never a multiply-by-zero
            zero = jnp.zeros((), jnp.float32)
            dq_i = jnp.where(keep, dq_i, zero)
            dk_i = jnp.where(keep, dk_i, zero)
            dv_i = jnp.where(keep, dv_i, zero)
        if step == 0:
            dq = dq_i.astype(jnp.float32)
            dk_acc = dk_i.astype(jnp.float32)
            dv_acc = dv_i.astype(jnp.float32)
        else:
            dq = dq + dq_i.astype(jnp.float32)
            dk_acc = dk_acc + dk_i.astype(jnp.float32)
            dv_acc = dv_acc + dv_i.astype(jnp.float32)
        # the travelling dk/dv accumulators rotate every step (n total hops
        # bring them home); kb/vb are dead after the last compute
        if step < n - 1:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)

    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


_RING_FLASH = None


def _get_ring_flash():
    """Build the custom-vjp-wrapped flash ring lazily (keeps this module's
    no-jax-at-import convention). One core serves both schedules; the
    per-step policy is selected by the static ``schedule``/``causal``
    nondiff args."""
    global _RING_FLASH
    if _RING_FLASH is not None:
        return _RING_FLASH
    import functools

    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
    def ring_flash(q, k, v, axis_name, scale, causal, schedule):
        out, _ = _ring_flash_impl(q, k, v, axis_name, scale,
                                  _ring_step_spec(schedule, causal))
        return out

    def fwd(q, k, v, axis_name, scale, causal, schedule):
        out, lse = _ring_flash_impl(q, k, v, axis_name, scale,
                                    _ring_step_spec(schedule, causal))
        return out, (q, k, v, out, lse)

    def bwd(axis_name, scale, causal, schedule, res, ct):
        # flash-block ring backward against the saved global lse — O(T_loc)
        # memory like the forward (no (T, T) score recomputation)
        q, k, v, out, lse = res
        return _ring_flash_bwd_impl(q, k, v, out, lse, ct, axis_name, scale,
                                    _ring_step_spec(schedule, causal))

    ring_flash.defvjp(fwd, bwd)
    _RING_FLASH = ring_flash
    return ring_flash


def stripe_sequence(x, n: int):
    """Global (B, T, ...) → striped layout: token t moves to stripe t % n,
    local slot t // n, so a contiguous n-way shard over axis 1 gives rank r
    the stripe {r, r+n, r+2n, ...} (Brandon et al., striped attention).
    Requires T % n == 0."""
    b, t = x.shape[0], x.shape[1]
    assert t % n == 0, f"T {t} not divisible by {n} stripes"
    rest = x.shape[2:]
    return (x.reshape((b, t // n, n) + rest)
            .swapaxes(1, 2)
            .reshape((b, t) + rest))


def unstripe_sequence(x, n: int):
    """Inverse of :func:`stripe_sequence`."""
    b, t = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    return (x.reshape((b, n, t // n) + rest)
            .swapaxes(1, 2)
            .reshape((b, t) + rest))


def striped_ring_attention(q, k, v, axis_name: str,
                           scale: Optional[float] = None):
    """CAUSAL ring attention over STRIPED sequence shards — the balanced
    schedule the round-1 advisor asked for: every rotation computes a
    diagonal-masked block (offset 0 for earlier-ranked stripes, -1 strict
    for later-ranked ones), so ~half the block FLOPs of the contiguous
    causal ring are simply never issued instead of being computed and
    nulled. Shards must be in stripe layout (:func:`stripe_sequence` on
    the global batch before sharding; :func:`unstripe_sequence` after).
    Differentiable; flash kernels both directions."""
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _get_ring_flash()(q, k, v, axis_name, float(sc), True, "striped")


def _ring_einsum(q, k, v, axis_name: str, causal: bool = False,
                 scale: Optional[float] = None):
    """The einsum-based ring (differentiable; materializes one (T, T)
    score block per step)."""
    import jax.numpy as jnp
    from jax import lax

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    from bigdl_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    q_off = my * T

    # online-softmax running state per (B, H, Tq), derived FROM q so the
    # accumulators inherit q's device-varying axes and the fori_loop carry
    # types line up with the permuted K/V blocks (jax 0.9 vma tracking)
    base = jnp.sum(q.astype(jnp.float32) * 0.0, axis=-1).transpose(0, 2, 1)
    m0 = base - jnp.inf                      # (B, H, T)
    l0 = base                                # (B, H, T)
    o0 = q.astype(jnp.float32) * 0.0         # (B, T, H, D)

    # ring: after `step` rotations this chip holds the K/V block that
    # ORIGINATED at axis index (my - step) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        kb, vb, m, l, o = carry
        src = (my - step) % n
        k_off = src * T
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb).astype(jnp.float32) * scale
        if causal:
            qpos = q_off + jnp.arange(T)
            kpos = k_off + jnp.arange(T)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                          s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        # blocks can be fully masked (-inf): keep the correction finite
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * corr + p.sum(-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return kb, vb, m_new, l, o

    _, _, _, l, o = lax.fori_loop(0, n, body, (k, v, m0, l0, o0))
    out = o / jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-20)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None,
                      use_flash: bool = False):
    """All-to-all sequence parallelism inside a ``shard_map``: re-shard
    (B, T_local, H, D) → (B, T_global, H_local, D), attend per head group,
    and re-shard back. Requires H divisible by the axis size.
    ``use_flash=True`` runs the per-head-group attention through the Pallas
    flash kernel (O(T) memory over the FULL gathered sequence)."""
    from jax import lax

    from bigdl_tpu.utils.compat import axis_size

    n = axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    def seq_to_heads(x):  # gather seq (axis 1), scatter heads (axis 2)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from bigdl_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal, scale=scale)
    else:
        out = _local_attention(qg, kg, vg, scale, causal)
    return heads_to_seq(out)
