"""AllReduceParameter — the distributed parameter plane, TPU-native.

Reference (UNVERIFIED, SURVEY.md §0):
``.../bigdl/parameters/AllReduceParameter.scala`` — flattens all parameters
into ONE 1-D tensor, slices it into ``nodeNumber`` partitions each owned by
one executor; per iteration ``putGradients`` + ``aggregateGradientPartition``
implement a reduce-scatter over Spark BlockManager, the owner runs the
optimizer on its slice, and ``sendWeightPartition``/``getWeights`` implement
the all-gather. FP16 compression (``FP16CompressedTensor``) halves exchange
bytes.

TPU-native redesign (the north star's core ask): the same partitioned-
optimizer dataflow as XLA collectives over ICI inside ONE compiled SPMD
program —

    putGradients + aggregateGradientPartition  →  lax.psum_scatter
    owner's optimMethod.optimize on its slice  →  update on the local shard
    sendWeightPartition + getWeights           →  lax.all_gather
    FP16CompressedTensor                       →  cast grads to bf16/f16
                                                  before the reduce-scatter

Parameters and optimizer slots live sharded (1/N per chip, ZeRO-1 style)
exactly as the reference keeps each partition on its owner. The simpler
``allreduce`` mode (plain ``psum`` + replicated update) is also provided;
numerics differ only in reduction order (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np


def flatten_params(params) -> Tuple[Any, Callable]:
    """Host-side: params pytree → (flat 1-D array, unravel fn)."""
    from jax.flatten_util import ravel_pytree

    flat, unravel = ravel_pytree(params)
    return flat, unravel


def pad_to_multiple(flat, n: int):
    """Pad a 1-D array so its length divides n (the partition arithmetic of
    ``object AllReduceParameter`` — taskSize/extraSize)."""
    import jax.numpy as jnp

    size = flat.shape[0]
    padded = ((size + n - 1) // n) * n
    if padded == size:
        return flat, 0
    return jnp.concatenate([flat, jnp.zeros((padded - size,), flat.dtype)]), padded - size


class AllReduceParameter:
    """Builder for the partitioned-parameter SPMD step pieces.

    Usage (inside a shard_map'd step over mesh axis ``axis_name``):

        arp = AllReduceParameter(params_template, n_partitions, axis_name)
        full = arp.get_weights(my_shard)          # all-gather -> pytree
        ... forward/backward -> grads pytree ...
        gshard = arp.aggregate_gradients(grads)   # reduce-scatter (mean)
        new_shard, new_opt = optim.update(gshard, opt_shard, my_shard)
    """

    def __init__(self, params_template, n_partitions: int, axis_name: str = "data",
                 compress: Optional[str] = None) -> None:
        import jax

        self.axis_name = axis_name
        self.n = n_partitions
        self.compress = compress  # None | "bf16" | "fp16"
        flat, self._unravel = flatten_params(params_template)
        self.total_size = int(flat.shape[0])
        self.padded_size = ((self.total_size + self.n - 1) // self.n) * self.n
        self.shard_size = self.padded_size // self.n
        self._leaves, self._treedef = jax.tree_util.tree_flatten(params_template)
        self._shapes = [l.shape for l in self._leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self._dtypes = [l.dtype for l in self._leaves]

    # -- host-side setup ---------------------------------------------------

    def init_shards(self, params) -> Any:
        """Host: full params → stacked per-partition slices (n, shard_size).
        Place with NamedSharding(P(axis)) so slice i lives on device i."""
        import jax.numpy as jnp

        flat, _ = flatten_params(params)
        flat, _pad = pad_to_multiple(flat, self.n)
        return flat.reshape(self.n, self.shard_size)

    def to_full(self, shards) -> Any:
        """Host: stacked shards → params pytree. In a multi-process run the
        stacked array spans non-addressable devices — gather every
        process's shards first (the pod analog of getWeights to driver)."""
        if getattr(shards, "is_fully_addressable", True) is False:
            from jax.experimental import multihost_utils

            shards = multihost_utils.process_allgather(shards, tiled=True)
        flat = np.asarray(shards).reshape(-1)[: self.total_size]
        return self._unravel(flat)

    # -- traced (inside shard_map) ----------------------------------------

    def _flatten_tree(self, tree):
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(tree)
        flat = jnp.concatenate([jnp.ravel(l) for l in leaves])
        if flat.shape[0] != self.padded_size:
            flat = jnp.concatenate(
                [flat, jnp.zeros((self.padded_size - flat.shape[0],), flat.dtype)]
            )
        return flat

    def _unflatten_tree(self, flat):
        import jax

        out, offset = [], 0
        for shape, size, dtype in zip(self._shapes, self._sizes, self._dtypes):
            out.append(flat[offset:offset + size].reshape(shape).astype(dtype))
            offset += size
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _make_gather(self):
        """all_gather with a custom vjp whose backward is the (optionally
        compressed) reduce-scatter. Differentiating the train loss w.r.t. the
        local weight shard therefore IS the reference dataflow:

            forward:  sendWeightPartition/getWeights  = all_gather
            backward: putGradients/aggregateGradient  = psum_scatter
            FP16CompressedTensor                      = bf16/f16 cast on the
                                                        cotangent exchange
        """
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        axis, compress = self.axis_name, self.compress

        @jax.custom_vjp
        def gather(shard):
            return lax.all_gather(shard, axis, tiled=True)

        def fwd(shard):
            return gather(shard), None

        def bwd(_, ct):
            orig = ct.dtype
            if compress == "bf16":
                ct = ct.astype(jnp.bfloat16)
            elif compress == "fp16":
                ct = ct.astype(jnp.float16)
            gshard = lax.psum_scatter(ct, axis, scatter_dimension=0, tiled=True)
            return (gshard.astype(orig),)

        gather.defvjp(fwd, bwd)
        return gather

    def get_weights(self, my_shard):
        """all-gather the weight partitions → full params pytree
        (reference ``getWeights`` + per-executor assembly). Differentiable:
        the cotangent path runs the compressed reduce-scatter."""
        if not hasattr(self, "_gather"):
            self._gather = self._make_gather()
        return self._unflatten_tree(self._gather(my_shard))
