"""ThreadPool — host-side task pool (reference ``utils/ThreadPool.scala``).

Reference role (UNVERIFIED, SURVEY.md §0): wraps a Java executor with
``invokeAndWait``/``invoke2`` and MKL thread-affinity plumbing; ``Engine``
owned two of them (``Engine.default`` for IO/comm, ``Engine.model`` for
compute).

TPU-native: XLA owns compute threads, so the pool exists only for HOST work
— parallel file IO, decode, checkpoint writes (the C++ prefetch executor in
``bigdl_tpu/native`` covers the hot input path). The reference call shapes
(``invoke_and_wait`` over a list of thunks) are preserved on top of
``concurrent.futures``.
"""

from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence


class ThreadPool:
    def __init__(self, n_threads: int) -> None:
        self.n_threads = n_threads
        self._pool = ThreadPoolExecutor(max_workers=n_threads)

    def invoke_and_wait(self, tasks: Sequence[Callable], timeout: Optional[float] = None):
        """Run all thunks, block for completion, return results in order
        (reference ``invokeAndWait``). ``timeout`` is an OVERALL deadline,
        not per task. Exceptions propagate."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        futures = [self._pool.submit(t) for t in tasks]
        out = []
        for f in futures:
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.0))
            out.append(f.result(remaining))
        return out

    def invoke(self, tasks: Sequence[Callable]) -> List[Future]:
        """Fire-and-return futures (reference ``invoke2``)."""
        return [self._pool.submit(t) for t in tasks]

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
