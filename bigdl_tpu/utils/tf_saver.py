"""TensorflowSaver — export a trained module as a TensorFlow artifact.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/tf/
TensorflowSaver.scala`` — walks the BigDL graph emitting GraphDef nodes
layer by layer.

TPU-native redesign: instead of a hand-written per-layer emitter, the pure
``apply`` IS the model — ``jax2tf`` stages the exact jitted computation
(same XLA program the TPU runs) into a TF function, which we persist as a
SavedModel and/or frozen GraphDef. Every layer the framework ever grows is
exportable for free, with numerics identical to the serving path.
"""

from __future__ import annotations

from typing import Optional, Sequence


def save_tf(module, input_shape: Sequence[int], path: str,
            frozen_graph: bool = False, batch: Optional[int] = None):
    """Export ``module`` (eval mode) to ``path``.

    ``input_shape`` excludes the batch dim (``batch=None`` → dynamic batch).
    ``frozen_graph=True`` writes a single frozen ``GraphDef`` protobuf file
    instead of a SavedModel directory. Returns the TF concrete function.
    """
    import tensorflow as tf
    from jax.experimental import jax2tf

    module._materialize_params()
    was_training = module.is_training()
    module.evaluate()
    params, state = module.params, module.state

    def forward(x):
        out, _ = module.apply(params, x, state, training=False, rng=None)
        return out

    poly = None
    if batch is None:  # dynamic batch → symbolic leading dim for jax2tf
        poly = ["(b, " + ", ".join(str(d) for d in input_shape) + ")"]
    tf_fn = tf.function(
        jax2tf.convert(forward, with_gradient=False,
                       polymorphic_shapes=poly,
                       # serve from any host: the artifact embeds per-platform
                       # lowerings, not just the exporting backend's
                       native_serialization_platforms=("cpu", "tpu")),
        input_signature=[tf.TensorSpec([batch] + list(input_shape), tf.float32)],
        autograph=False,
    )
    conc = tf_fn.get_concrete_function()

    if frozen_graph:
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        frozen = convert_variables_to_constants_v2(conc)
        tf.io.write_graph(frozen.graph.as_graph_def(), ".", path,
                          as_text=False)
    else:
        wrapper = tf.Module()
        wrapper.f = tf_fn
        tf.saved_model.save(wrapper, path,
                            signatures={"serving_default": conc})
    if was_training:
        module.training()
    return conc


class TensorflowSaver:
    """Reference-shaped facade (``TensorflowSaver.saveGraph``)."""

    save_graph = staticmethod(save_tf)
