"""Engine — global runtime configuration singleton.

Reference role (UNVERIFIED citation, see SURVEY.md §0):
``spark/dl/src/main/scala/com/intel/analytics/bigdl/utils/Engine.scala`` —
``object Engine`` parses SparkConf + ``bigdl.*`` system properties into
``nodeNumber`` / ``coreNumber`` / ``engineType`` and owns the compute thread
pools. The north star adds ``EngineType.TPU`` here exactly the way
``MklDnn`` was added alongside ``MklBlas``.

TPU-native redesign: there are no executor JVMs or thread pools to manage —
XLA owns the chip. ``Engine`` instead owns *device topology*: it discovers
``jax.devices()``, validates the requested node/core counts against them, and
hands out ``jax.sharding.Mesh`` objects that every distributed component
(DistriOptimizer, AllReduceParameter, sequence/tensor parallel layers) builds
on. Configuration mirrors the reference's ``bigdl.*`` system-property tier as
``BIGDL_*`` environment variables.
"""

from __future__ import annotations

import os
from enum import Enum
from typing import Optional, Sequence


class EngineType(Enum):
    """Compute-engine selector.

    Reference: ``EngineType`` sealed trait with ``MklBlas`` / ``MklDnn``
    (utils/Engine.scala). ``TPU`` is the new native engine; the two MKL
    values are accepted for source compatibility and execute on whatever
    backend JAX has (they do NOT call MKL — on this framework all math
    lowers to XLA).
    """

    MklBlas = "mklblas"
    MklDnn = "mkldnn"
    TPU = "tpu"

    @staticmethod
    def parse(name: str) -> "EngineType":
        key = name.strip().lower()
        for e in EngineType:
            if e.value == key or e.name.lower() == key:
                return e
        raise ValueError(f"unknown engine type: {name!r}")


def _env(name: str, default=None):
    return os.environ.get(name, default)


class _EngineSingleton:
    """Process-wide runtime state. Mirrors ``object Engine``."""

    def __init__(self) -> None:
        import threading

        self._initialized = False
        self._distributed_initialized = False
        self._default_pool = None
        self._pool_lock = threading.Lock()
        self._node_number = 1
        self._core_number = 1
        self._engine_type = EngineType.TPU
        self._local_mode = True
        self._seed: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    def init(
        self,
        node_number: Optional[int] = None,
        core_number: Optional[int] = None,
        engine_type: Optional[EngineType | str] = None,
        local_mode: Optional[bool] = None,
    ) -> "_EngineSingleton":
        """Validate and freeze the runtime topology.

        Reference: ``Engine.init`` validates executor topology from
        SparkConf; here ``node_number`` is the number of JAX processes
        (multi-host) and ``core_number`` the number of local devices each
        drives. Defaults come from ``BIGDL_*`` env vars then from the live
        JAX backend.
        """
        import jax

        if engine_type is None:
            engine_type = _env("BIGDL_ENGINE_TYPE", "tpu")
        if isinstance(engine_type, str):
            engine_type = EngineType.parse(engine_type)
        self._engine_type = engine_type

        if node_number is None:
            node_number = int(_env("BIGDL_NODE_NUMBER", jax.process_count()))
        if core_number is None:
            core_number = int(_env("BIGDL_CORE_NUMBER", jax.local_device_count()))
        if node_number < 1 or core_number < 1:
            raise ValueError(
                f"invalid topology: node_number={node_number} core_number={core_number}"
            )
        self._node_number = node_number
        self._core_number = core_number
        self._local_mode = (
            local_mode
            if local_mode is not None
            else _env("BIGDL_LOCAL_MODE", str(node_number == 1)).lower()
            in ("1", "true")
        )
        seed = _env("BIGDL_SEED")
        if seed is not None:
            self._seed = int(seed)
        self._initialized = True
        return self

    def init_distributed(self, coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         **init_kw) -> "_EngineSingleton":
        """Multi-host pod initialization: start the JAX distributed runtime
        (one process per host, ICI within a slice / DCN across) and then run
        the normal :meth:`init` topology validation.

        The reference analog is ``Engine.createSparkConf`` + ``Engine.init``
        forcing full executor registration before training
        (``minRegisteredResourcesRatio=1.0``) — ``jax.distributed.initialize``
        blocks until every process joins, giving the same guarantee.
        Parameters default to TPU auto-detection (env-provided) when None.
        """
        import jax

        if self._distributed_initialized:  # idempotent like init()
            return self.init()
        if self._initialized:
            raise RuntimeError(
                "Engine.init_distributed() must run BEFORE Engine.init() or "
                "any model/JAX work — jax.distributed.initialize cannot run "
                "once the XLA backend is up. Call it first in your main.")
        kw = dict(init_kw)
        if coordinator_address is not None:
            kw["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kw["num_processes"] = num_processes
        if process_id is not None:
            kw["process_id"] = process_id
        jax.distributed.initialize(**kw)
        self._distributed_initialized = True
        return self.init()

    def _ensure_init(self) -> None:
        if not self._initialized:
            self.init()

    # -- host thread pools (reference Engine.default / Engine.model) -------

    def default_pool(self):
        """Host IO/comm pool (reference ``Engine.default``); compute has no
        pool here — XLA owns the chip's threads."""
        if self._default_pool is None:
            from bigdl_tpu.utils.thread_pool import ThreadPool

            self._ensure_init()
            with self._pool_lock:  # concurrent first calls race otherwise
                if self._default_pool is None:
                    self._default_pool = ThreadPool(max(self._core_number, 1))
        return self._default_pool

    # reference name kept: Engine.model was the compute pool; host-side it
    # aliases the same pool (compute threading belongs to XLA)
    model_pool = default_pool

    def reset(self) -> None:
        """Testing hook: forget topology so the next init() re-discovers."""
        self._initialized = False
        if self._default_pool is not None:  # pool is topology-sized
            self._default_pool.shutdown()
            self._default_pool = None

    # -- topology accessors ------------------------------------------------

    def node_number(self) -> int:
        self._ensure_init()
        return self._node_number

    def core_number(self) -> int:
        self._ensure_init()
        return self._core_number

    def engine_type(self) -> EngineType:
        self._ensure_init()
        return self._engine_type

    def is_local_mode(self) -> bool:
        self._ensure_init()
        return self._local_mode

    def device_count(self) -> int:
        """Total chips visible to this process group."""
        import jax

        return jax.device_count()

    def devices(self):
        import jax

        return jax.devices()

    # -- mesh construction -------------------------------------------------

    def mesh(
        self,
        axis_names: Sequence[str] = ("data",),
        axis_sizes: Optional[Sequence[int]] = None,
        devices=None,
    ):
        """Build a ``jax.sharding.Mesh`` over the visible devices.

        The default is a 1-D data-parallel mesh over every chip — the
        TPU-native analog of the reference's "one partition owner per
        executor" layout (parameters/AllReduceParameter.scala). Pass
        ``axis_names=("data","model")`` etc. for hybrid layouts.
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        n = len(devices)
        if axis_sizes is None:
            axis_sizes = [n] + [1] * (len(axis_names) - 1)
        if int(np.prod(axis_sizes)) != n:
            raise ValueError(
                f"axis_sizes {tuple(axis_sizes)} do not cover {n} devices"
            )
        dev_array = np.asarray(devices).reshape(axis_sizes)
        return Mesh(dev_array, tuple(axis_names))

    def hybrid_mesh(
        self,
        ici_axis_names: Sequence[str] = ("data",),
        ici_axis_sizes: Optional[Sequence[int]] = None,
        dcn_axis_name: str = "dcn",
        num_slices: Optional[int] = None,
        devices=None,
    ):
        """Two-level multi-slice mesh: a leading DCN axis across pod slices
        and ICI axes within each slice.

        Lay data parallelism on ``dcn_axis_name`` and model/sequence/expert
        axes on the ICI axes — then every heavy collective (psum_scatter,
        all_gather, all_to_all) stays on ICI links and only the small
        cross-slice gradient reduction rides DCN. Slices are detected from
        ``device.slice_index`` when exposed (real multi-slice TPU jobs);
        pass ``num_slices`` explicitly to partition a flat device list
        (CPU simulation).
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        slice_ids = [getattr(d, "slice_index", None) for d in devices]
        if num_slices is None:
            num_slices = (len({s for s in slice_ids})
                          if slice_ids and slice_ids[0] is not None else 1)
        n = len(devices)
        if n % num_slices:
            raise ValueError(f"{n} devices do not split into {num_slices} slices")
        per_slice = n // num_slices
        if ici_axis_sizes is None:
            ici_axis_sizes = [per_slice] + [1] * (len(ici_axis_names) - 1)
        if int(np.prod(ici_axis_sizes)) != per_slice:
            raise ValueError(
                f"ici_axis_sizes {tuple(ici_axis_sizes)} do not cover the "
                f"{per_slice} devices of one slice")
        if slice_ids and slice_ids[0] is not None and num_slices > 1:
            # group devices so each leading-axis row is one physical slice
            order = sorted(range(n), key=lambda i: (slice_ids[i],
                                                    getattr(devices[i], "id", i)))
            devices = [devices[i] for i in order]
        dev = np.asarray(devices).reshape([num_slices] + list(ici_axis_sizes))
        return Mesh(dev, (dcn_axis_name, *ici_axis_names))

    # -- misc --------------------------------------------------------------

    def set_seed(self, seed: int) -> None:
        self._seed = seed

    def seed(self) -> Optional[int]:
        return self._seed


Engine = _EngineSingleton()
