from bigdl_tpu.utils.engine import Engine, EngineType
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.file_io import File
from bigdl_tpu.utils.random_gen import RandomGenerator, RNG

__all__ = ["Engine", "EngineType", "Table", "T", "File", "RandomGenerator", "RNG"]
