"""File — snapshot save/load for modules, optim methods and raw objects.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/File.scala`` —
Java-serialization save/load to local FS or HDFS; backs ``Module.save`` and
checkpoint snapshots.

TPU-native redesign: pickle for object structure with every ``jax.Array``
converted to host numpy on save and restored lazily on load (device placement
happens on first use — there is no need to pin arrays to a chip inside a
snapshot). Atomic write (tmp + rename) so a preempted checkpoint never leaves
a torn file, which is what the DistriOptimizer retry loop (SURVEY.md §5.3)
relies on.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any

import numpy as np


def _to_host(obj: Any) -> Any:
    """Recursively convert jax arrays to numpy for serialization."""
    import jax

    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(conv, obj)


class _File:
    def save(self, obj: Any, path: str, over_write: bool = False) -> None:
        if os.path.exists(path) and not over_write:
            raise FileExistsError(
                f"{path} already exists; pass over_write=True to replace it"
            )
        payload = _to_host(obj)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load(self, path: str) -> Any:
        with open(path, "rb") as f:
            return pickle.load(f)


File = _File()
