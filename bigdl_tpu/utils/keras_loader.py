"""Keras 1.2 model import — the reference's ``Model.load_keras``.

Reference (UNVERIFIED, SURVEY.md §0): pyspark ``bigdl.nn.layer.Model
.load_keras(json_path, hdf5_path)`` + the ``bigdl/keras`` converter package
— BigDL 0.x could ingest a Keras 1.2.2 architecture (``model.to_json()``)
and its HDF5 weights and return an equivalent BigDL model (the §4
"Keras-compat tests compare against recorded Keras 1.2 outputs" harness
exercised exactly this path).

TPU-native placement: the importer targets this framework's own
``bigdl_tpu.nn.keras`` layer set (which compiles to one XLA program like
everything else); nothing Keras-side is executed — the JSON is parsed
directly and the HDF5 is read with h5py, so no TF/Keras dependency.

Scope (documented, enforced with clear errors):

* architectures — ``Sequential`` and functional ``Model`` configs, over
  the layer table below (the keras1 layers the reference converter
  itself handled); unsupported class names raise with the name.
* weights — Sequential models, for Dense / Convolution1D/2D /
  BatchNormalization (keras1 stored [gamma, beta, running_mean,
  running_std] where ``running_std`` is in fact the running VARIANCE —
  keras 1.2's ``batch_normalization`` passes it as var) / Embedding /
  LSTM / SimpleRNN / GRU (gate identity parsed from the keras1 weight
  NAMES, robust to list ordering; the keras-compat GRU layer runs the
  keras1 reset-before-candidate cell, so GRU import is exact).
  Functional-model weights raise NotImplementedError.
* ``dim_ordering``: ``"th"`` maps 1:1 (this framework is CHW/NCHW, the
  reference's own convention); ``"tf"`` configs get their input shapes
  and conv kernels transposed to CHW — the loaded model expects CHW
  inputs.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np


def _strip_batch(shape) -> tuple:
    return tuple(int(s) for s in shape[1:])


def _to_chw(shape: tuple, dim_ordering: str) -> tuple:
    if dim_ordering == "tf" and len(shape) == 3:
        h, w, c = shape
        return (c, h, w)
    return shape


def _activation_name(cfg: Dict[str, Any]) -> Optional[str]:
    act = cfg.get("activation")
    return None if act in (None, "linear") else act


class _Unsupported(ValueError):
    pass


def _build_layer(class_name: str, cfg: Dict[str, Any],
                 input_shape: Optional[tuple]):
    """keras1 layer config → bigdl_tpu.nn.keras layer (not yet built)."""
    from bigdl_tpu.nn import keras as K

    dim_ordering = cfg.get("dim_ordering", "th")
    kw = {}
    if input_shape is not None:
        kw["input_shape"] = input_shape

    if class_name == "Dense":
        return K.Dense(cfg["output_dim"], activation=_activation_name(cfg),
                       bias=cfg.get("bias", True), **kw)
    if class_name == "Activation":
        return K.Activation(cfg["activation"], **kw)
    if class_name == "Dropout":
        return K.Dropout(cfg["p"], **kw)
    if class_name == "Flatten":
        return K.Flatten(**kw)
    if class_name == "Reshape":
        return K.Reshape(tuple(cfg["target_shape"]), **kw)
    if class_name == "Permute":
        return K.Permute(tuple(cfg["dims"]), **kw)
    if class_name == "RepeatVector":
        return K.RepeatVector(cfg["n"], **kw)
    if class_name == "Highway":
        return K.Highway(activation=_activation_name(cfg), **kw)
    if class_name == "Masking":
        return K.Masking(cfg.get("mask_value", 0.0), **kw)
    if class_name == "Convolution1D":
        return K.Convolution1D(
            cfg["nb_filter"], cfg["filter_length"],
            subsample_length=cfg.get("subsample_length", 1),
            border_mode=cfg.get("border_mode", "valid"),
            activation=_activation_name(cfg),
            bias=cfg.get("bias", True), **kw)
    if class_name == "Convolution2D":
        return K.Convolution2D(
            cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
            subsample=tuple(cfg.get("subsample", (1, 1))),
            border_mode=cfg.get("border_mode", "valid"),
            activation=_activation_name(cfg),
            bias=cfg.get("bias", True), **kw)
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        cls = getattr(K, class_name)
        return cls(pool_size=tuple(cfg.get("pool_size", (2, 2))),
                   strides=(tuple(cfg["strides"])
                            if cfg.get("strides") else None),
                   border_mode=cfg.get("border_mode", "valid"), **kw)
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        cls = getattr(K, class_name)
        return cls(pool_length=cfg.get("pool_length", 2),
                   stride=cfg.get("stride"),
                   border_mode=cfg.get("border_mode", "valid"), **kw)
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return getattr(K, class_name)(**kw)
    if class_name == "BatchNormalization":
        if cfg.get("mode", 0) != 0:
            raise _Unsupported(
                "BatchNormalization mode!=0 (keras1 legacy modes)")
        return K.BatchNormalization(epsilon=cfg.get("epsilon", 1e-3),
                                    momentum=cfg.get("momentum", 0.99), **kw)
    if class_name == "Embedding":
        return K.Embedding(cfg["input_dim"], cfg["output_dim"], **kw)
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        cls = getattr(K, class_name)
        return cls(cfg["output_dim"],
                   return_sequences=cfg.get("return_sequences", False), **kw)
    if class_name == "ZeroPadding2D":
        return K.ZeroPadding2D(tuple(cfg.get("padding", (1, 1))), **kw)
    if class_name == "UpSampling2D":
        return K.UpSampling2D(tuple(cfg.get("size", (2, 2))), **kw)
    if class_name == "Merge":
        return K.Merge(mode=cfg.get("mode", "sum"),
                       concat_axis=cfg.get("concat_axis", -1))
    if class_name in ("LeakyReLU",):
        return K.LeakyReLU(cfg.get("alpha", 0.3), **kw)
    if class_name in ("ELU",):
        return K.ELU(cfg.get("alpha", 1.0), **kw)
    if class_name in ("ThresholdedReLU",):
        return K.ThresholdedReLU(cfg.get("theta", 1.0), **kw)
    if class_name in ("GaussianNoise",):
        return K.GaussianNoise(cfg.get("sigma", cfg.get("stddev", 0.1)),
                               **kw)
    if class_name in ("GaussianDropout",):
        return K.GaussianDropout(cfg.get("p", cfg.get("rate", 0.1)), **kw)
    raise _Unsupported(
        f"keras layer {class_name!r} is not supported by load_keras "
        "(see utils/keras_loader.py for the supported table)")


def _build_sequential(layer_cfgs: List[Dict[str, Any]]):
    from bigdl_tpu.nn import keras as K

    model = K.Sequential()
    first = True
    for entry in layer_cfgs:
        cname, cfg = entry["class_name"], entry["config"]
        input_shape = None
        if first:
            bis = cfg.get("batch_input_shape")
            if bis is None:
                raise ValueError(
                    "first keras layer carries no batch_input_shape")
            input_shape = _to_chw(_strip_batch(bis),
                                  cfg.get("dim_ordering", "th"))
            first = False
        model.add(_build_layer(cname, cfg, input_shape))
    return model


def _build_functional(config: Dict[str, Any]):
    from bigdl_tpu.nn import keras as K

    nodes: Dict[str, Any] = {}  # layer name -> KerasNode (output port 0)
    for entry in config["layers"]:
        cname, cfg, name = (entry["class_name"], entry["config"],
                            entry["name"])
        inbound = entry.get("inbound_nodes") or []
        if cname == "InputLayer":
            shape = _to_chw(_strip_batch(cfg["batch_input_shape"]),
                            cfg.get("dim_ordering", "th"))
            nodes[name] = K.Input(shape)
            continue
        if len(inbound) != 1:
            raise _Unsupported(
                f"layer {name!r} is applied {len(inbound)} times (shared "
                "keras layer) — load_keras supports single-application "
                "functional graphs")
        for ref in inbound[0]:
            if len(ref) > 1 and (ref[1] != 0 or (len(ref) > 2 and
                                                 ref[2] != 0)):
                raise _Unsupported(
                    f"layer {name!r} consumes node port {ref[1:]} of "
                    f"{ref[0]!r} — multi-application/multi-output "
                    "references are not supported")
        srcs = [nodes[ref[0]] for ref in inbound[0]]
        layer = _build_layer(cname, cfg, None)
        nodes[name] = layer(srcs if len(srcs) > 1 else srcs[0])
    def _ref(r):
        return nodes[r[0]]

    ins = [_ref(r) for r in config["input_layers"]]
    outs = [_ref(r) for r in config["output_layers"]]
    return K.Model(input=ins if len(ins) > 1 else ins[0],
                   output=outs if len(outs) > 1 else outs[0])


def load_keras_json(json_str: str):
    """Build a model from a Keras-1.2 ``model.to_json()`` string."""
    blob = json.loads(json_str)
    cls = blob.get("class_name")
    if cls == "Sequential":
        return _build_sequential(blob["config"])
    if cls == "Model":
        return _build_functional(blob["config"])
    raise ValueError(f"not a keras model json (class_name={cls!r})")


# -- weights ---------------------------------------------------------------

# classes whose keras1 save carries weight arrays (supported or not —
# missing arrays for any of these means a mismatched json/h5 pair)
_WEIGHTED_CLASSES = frozenset({
    "Dense", "Convolution1D", "Convolution2D", "BatchNormalization",
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Highway",
})

def _h5_layer_weights(f) -> Dict[str, List]:
    """keras1 HDF5 layout: root attr ``layer_names``; one group per layer
    with attr ``weight_names``. Returns ``(weight_name, array)`` pairs —
    recurrent-gate conversion keys off the NAMES (``.._W_i``/``.._U_f``),
    which is robust to keras1's odd list ordering."""
    root = f["model_weights"] if "model_weights" in f else f
    out = {}
    for lname in [n.decode() if isinstance(n, bytes) else n
                  for n in root.attrs.get("layer_names", [])]:
        g = root[lname]
        wnames = [n.decode() if isinstance(n, bytes) else n
                  for n in g.attrs.get("weight_names", [])]
        out[lname] = [(w, np.asarray(g[w])) for w in wnames]
    return out


def _named_gates(named, kind: str, gates: str) -> Optional[Dict[str, np.ndarray]]:
    """Pick keras1 recurrent arrays by name suffix ``_{kind}_{gate}``
    (e.g. ``lstm_1_W_i``); None when any gate is missing."""
    out = {}
    for g in gates:
        hits = [a for n, a in named if n.endswith(f"_{kind}_{g}")]
        if len(hits) != 1:
            return None
        out[g] = hits[0]
    return out


def _convert_weights(class_name: str, cfg: Dict[str, Any],
                     named: List):
    """keras1 (name, array) pairs → (param updates, state updates)."""
    dim_ordering = cfg.get("dim_ordering", "th")
    arrays = [a for _, a in named]
    if class_name == "LSTM":
        # keras1 LSTM math is the standard cell (ours, torch gate order
        # i,f,g,o); gate identity parsed from the weight names
        W = _named_gates(named, "W", "ifco")
        U = _named_gates(named, "U", "ifco")
        b = _named_gates(named, "b", "ifco")
        if not (W and U and b):
            raise NotImplementedError(
                "load_keras: LSTM weight names do not follow the keras1 "
                "_W_i/_U_f/_b_c pattern — cannot identify gates")
        order = "ifco"  # our fused layout: i, f, g(=keras c), o
        p = {
            "w_ih": np.concatenate([W[g].T for g in order]),
            "w_hh": np.concatenate([U[g].T for g in order]),
            "b_ih": np.concatenate([b[g] for g in order]),
            "b_hh": np.zeros(sum(b[g].size for g in order), np.float32),
        }
        return p, {}
    if class_name == "SimpleRNN":
        Ws = [a for n, a in named if n.endswith("_W")]
        Us = [a for n, a in named if n.endswith("_U")]
        bs = [a for n, a in named if n.endswith("_b")]
        if not (len(Ws) == len(Us) == len(bs) == 1):
            raise NotImplementedError(
                "load_keras: SimpleRNN weight names do not follow the "
                "keras1 _W/_U/_b pattern")
        return {"w_ih": Ws[0].T, "w_hh": Us[0].T, "b_ih": bs[0],
                "b_hh": np.zeros(bs[0].size, np.float32)}, {}
    if class_name == "GRU":
        # keras1 gate names z (update), r (reset), h (candidate); the
        # keras-compat GRU layer runs the keras1 reset-before-candidate
        # cell (recurrent.GRU reset_after=False), so the import is exact.
        # Our fused layout orders gates r, z, n
        W = _named_gates(named, "W", "zrh")
        U = _named_gates(named, "U", "zrh")
        b = _named_gates(named, "b", "zrh")
        if not (W and U and b):
            raise NotImplementedError(
                "load_keras: GRU weight names do not follow the keras1 "
                "_W_z/_U_r/_b_h pattern — cannot identify gates")
        order = "rzh"
        p = {
            "w_ih": np.concatenate([W[g].T for g in order]),
            "w_hh": np.concatenate([U[g].T for g in order]),
            "b_ih": np.concatenate([b[g] for g in order]),
            "b_hh": np.zeros(sum(b[g].size for g in order), np.float32),
        }
        return p, {}
    if class_name == "Dense":
        p = {"weight": arrays[0].T}
        if len(arrays) > 1:
            p["bias"] = arrays[1]
        return p, {}
    if class_name == "Convolution2D":
        k = arrays[0]
        if dim_ordering == "tf":          # (r, c, in, out) -> OIHW
            k = np.transpose(k, (3, 2, 0, 1))
        p = {"weight": k}
        if len(arrays) > 1:
            p["bias"] = arrays[1]
        return p, {}
    if class_name == "Convolution1D":
        # keras1 1-D kernel: (filter_length, 1, in, out) -> (out, in, L)
        k = arrays[0]
        if k.ndim == 4:
            k = np.transpose(k[:, 0], (2, 1, 0))
        p = {"weight": k}
        if len(arrays) > 1:
            p["bias"] = arrays[1]
        return p, {}
    if class_name == "BatchNormalization":
        gamma, beta, mean, var = arrays  # keras1 "running_std" IS variance
        return ({"weight": gamma, "bias": beta},
                {"running_mean": mean, "running_var": var})
    if class_name == "Embedding":
        return {"weight": arrays[0]}, {}
    raise NotImplementedError(
        f"load_keras: weight import for {class_name!r} is not supported "
        "(architecture was built; set weights manually or retrain)")


def _locate_subdict(tree, key: str):
    """The unique nested dict holding ``key`` as a direct entry."""
    hits = []

    def walk(t):
        if isinstance(t, dict):
            if key in t and not isinstance(t[key], dict):
                hits.append(t)
            for v in t.values():
                walk(v)
        elif isinstance(t, (list, tuple)):
            for v in t:
                walk(v)

    walk(tree)
    return hits[0] if len(hits) == 1 else None


def _apply_updates(tree, layer_index: int, updates: Dict[str, np.ndarray],
                   anchor: str):
    """Replace ``updates`` inside layer ``layer_index``'s subtree of
    ``tree`` IN PLACE (keyed ``<index>:<AutoName>`` by the Sequential
    container) — the caller deep-copies the tree once up front."""
    prefix = f"{layer_index}:"
    sub_key = next((k for k in tree if str(k).startswith(prefix)), None)
    if sub_key is None:
        raise ValueError(
            f"load_keras: no parameter subtree for layer {layer_index}")
    target = _locate_subdict(tree[sub_key], anchor)
    if target is None:
        raise ValueError(
            f"load_keras: could not locate the {anchor!r}-holding params "
            f"of layer {layer_index} unambiguously")
    for k, v in updates.items():
        if k not in target:
            # inserting an orphan key would "load successfully" while the
            # layer never reads it (e.g. h5 bias vs bias=false json)
            raise ValueError(
                f"load_keras: layer {layer_index} has no parameter {k!r} "
                f"(built params: {sorted(target)}) — the json/h5 pair "
                "does not match")
        if tuple(np.shape(target[k])) != tuple(v.shape):
            raise ValueError(
                f"load_keras: layer {layer_index} weight {k!r} shape "
                f"{v.shape} does not match the built model's "
                f"{np.shape(target[k])}")
        target[k] = v.astype(np.float32)
    return tree


def load_keras(json_path: Optional[str] = None,
               hdf5_path: Optional[str] = None):
    """Reference ``Model.load_keras(json_path, hdf5_path)``: build the
    architecture from the JSON definition and, when ``hdf5_path`` is
    given, load the Keras-1.2 HDF5 weights into it (Sequential models)."""
    if json_path is None:
        raise ValueError("load_keras needs json_path")
    with open(json_path) as f:
        json_str = f.read()
    model = load_keras_json(json_str)
    if hdf5_path is None:
        return model

    blob = json.loads(json_str)
    if blob["class_name"] != "Sequential":
        raise NotImplementedError(
            "load_keras: weight import is supported for Sequential models "
            "(functional architectures import without weights)")
    import h5py

    with h5py.File(hdf5_path, "r") as f:
        by_layer = _h5_layer_weights(f)

    model._materialize_params()
    import copy

    # one up-front copy; _apply_updates then mutates in place (a copy per
    # layer would be O(layers x model size))
    params = copy.deepcopy(model.params)
    state = copy.deepcopy(model.state)
    consumed = set()
    # tf-dim_ordering bookkeeping: the builder converts input shapes and
    # conv kernels to CHW, so the model FLATTENS in CHW order — but a
    # keras1 tf-ordered save's first post-Flatten Dense kernel has its
    # rows in HWC-flat order (the classic th/tf conversion pitfall).
    # Track the Flatten of tf-ordered spatial features and permute that
    # Dense kernel's input rows HWC-flat -> CHW-flat.
    pending_perm = None
    cur_tf = False
    for i, entry in enumerate(blob["config"]):
        cname, cfg = entry["class_name"], entry["config"]
        if "dim_ordering" in cfg:
            cur_tf = cfg["dim_ordering"] == "tf"
        if cname == "Flatten":
            shp = model.layers[i].input_shape
            if cur_tf and shp is not None and len(shp) == 3:
                c, h, w = shp
                # perm[chw_flat_position] = hwc_flat_row of the keras kernel
                pending_perm = np.arange(h * w * c).reshape(
                    (h, w, c)).transpose(2, 0, 1).ravel()
            else:
                pending_perm = None
        lname = cfg.get("name", "")
        arrays = by_layer.get(lname)
        if not arrays:
            if cname in _WEIGHTED_CLASSES:
                # silently returning random weights would "load
                # successfully" and predict garbage — fail loudly
                raise ValueError(
                    f"load_keras: weight-bearing layer {lname!r} "
                    f"({cname}) has no weights in {hdf5_path!r} — the "
                    "json/h5 pair does not match (HDF5 layers: "
                    f"{sorted(by_layer)})")
            continue
        consumed.add(lname)
        p_upd, s_upd = _convert_weights(cname, cfg, arrays)
        if cname in _WEIGHTED_CLASSES and pending_perm is not None:
            if cname == "Dense":
                if "weight" in p_upd:
                    p_upd["weight"] = p_upd["weight"][:, pending_perm]
                pending_perm = None  # downstream features are 1-D again
            elif cname == "BatchNormalization":
                # per-feature vectors reorder the same way; the features
                # STAY HWC-flat afterwards, so the perm remains pending
                # for the eventual Dense
                p_upd = {k: v[pending_perm] for k, v in p_upd.items()}
                s_upd = {k: v[pending_perm] for k, v in s_upd.items()}
            else:
                raise NotImplementedError(
                    f"load_keras: tf-dim_ordering Flatten followed by "
                    f"{cname} — permuting this layer's weights from "
                    "HWC-flat to CHW-flat feature order is not "
                    "implemented; loading unpermuted weights would "
                    "silently predict garbage")
        if p_upd:
            params = _apply_updates(params, i, p_upd,
                                    anchor=next(iter(p_upd)))
        if s_upd:
            state = _apply_updates(state, i, s_upd,
                                   anchor=next(iter(s_upd)))
    orphans = {n for n, a in by_layer.items() if a} - consumed
    if orphans:
        raise ValueError(
            f"load_keras: HDF5 layers {sorted(orphans)} have weights but "
            "match no layer in the json — the json/h5 pair does not match")
    model.params = params
    model.state = state
    return model
