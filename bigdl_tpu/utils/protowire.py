"""Shared protobuf wire-format encoding primitives.

Used by the TensorBoard event writer (``visualization/tensorboard.py``) and
the Caffe exporter (``utils/caffe_loader.py``) — one definition of the
varint/tag/length-delimited rules so encoders can't drift.
"""

from __future__ import annotations

import struct


def varint(x: int) -> bytes:
    if x < 0:
        raise ValueError(f"varint fields must be non-negative, got {x}")
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def tag(fnum: int, wtype: int) -> bytes:
    return varint((fnum << 3) | wtype)


def field_varint(fnum: int, val: int) -> bytes:
    return tag(fnum, 0) + varint(val)


def field_double(fnum: int, val: float) -> bytes:
    return tag(fnum, 1) + struct.pack("<d", val)


def field_float(fnum: int, val: float) -> bytes:
    return tag(fnum, 5) + struct.pack("<f", val)


def field_bytes(fnum: int, val: bytes) -> bytes:
    return tag(fnum, 2) + varint(len(val)) + val
