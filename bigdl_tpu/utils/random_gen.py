"""RandomGenerator — seeded RNG plumbing.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/RandomGenerator.scala``
— per-thread Mersenne-Twister with ``RNG.setSeed``.

TPU-native redesign: JAX uses splittable counter-based keys, not stateful
generators; statefulness would break trace-once jit semantics. ``RNG`` keeps
one root key per process and hands out fresh subkeys (``next_key``), which is
what module init and dropout consume. Inside jitted train steps keys are
threaded functionally; ``RNG`` only feeds the host-side entry points.
"""

from __future__ import annotations

from typing import Optional


class RandomGenerator:
    def __init__(self, seed: int = 0) -> None:
        self._seed = seed
        self._key = None
        self._count = 0

    def set_seed(self, seed: int) -> "RandomGenerator":
        self._seed = seed
        self._key = None
        self._count = 0
        return self

    def get_seed(self) -> int:
        return self._seed

    def _root(self):
        import jax

        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def next_key(self):
        """A fresh independent PRNG key (deterministic given the seed)."""
        import jax

        k = jax.random.fold_in(self._root(), self._count)
        self._count += 1
        return k

    def uniform(self, low: float, high: float, shape=(), dtype=None):
        import jax

        return jax.random.uniform(
            self.next_key(), shape, minval=low, maxval=high,
            dtype=dtype or "float32",
        )

    def normal(self, mean: float, stdv: float, shape=(), dtype=None):
        import jax

        return mean + stdv * jax.random.normal(
            self.next_key(), shape, dtype=dtype or "float32"
        )


RNG = RandomGenerator()
