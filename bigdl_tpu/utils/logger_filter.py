"""LoggerFilter — route chatty framework logs to a file, keep ours on console.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/LoggerFilter.scala``
— log4j surgery sending verbose Spark INFO to ``bigdl.log`` while BigDL's
per-iteration INFO stays on the console.

TPU-native equivalents of "chatty Spark": jax's bridge/compiler warnings,
tensorflow, absl, orbax. ``LoggerFilter.redirect_spark_info_logs()`` (name
kept from the reference API) moves them to ``bigdl.log`` in the given
directory and pins ``bigdl_tpu``'s INFO to the console.
"""

from __future__ import annotations

import logging
import os
from typing import Iterable, Optional

_CHATTY = ("jax", "jax._src", "tensorflow", "absl", "orbax", "h5py")


class LoggerFilter:
    _configured = False

    @staticmethod
    def redirect_spark_info_logs(log_dir: str = ".",
                                 chatty: Optional[Iterable[str]] = None,
                                 filename: str = "bigdl.log") -> str:
        """Send chatty third-party INFO/WARNING logs to ``log_dir/bigdl.log``
        and keep ``bigdl_tpu`` INFO on the console. Returns the log path."""
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, filename)
        file_handler = logging.FileHandler(path)
        file_handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s - %(message)s"))

        for name in (chatty if chatty is not None else _CHATTY):
            lg = logging.getLogger(name)
            lg.handlers = [file_handler]
            lg.propagate = False
            lg.setLevel(logging.INFO)

        ours = logging.getLogger("bigdl_tpu")
        if not any(isinstance(h, logging.StreamHandler)
                   and not isinstance(h, logging.FileHandler)
                   for h in ours.handlers):
            console = logging.StreamHandler()
            console.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s - %(message)s"))
            ours.addHandler(console)
        ours.setLevel(logging.INFO)
        LoggerFilter._configured = True
        return path
