"""Version-compat shims for jax APIs that moved between releases.

The SPMD plane targets three generations of jax at once:

* ``shard_map`` lived in ``jax.experimental.shard_map`` through the
  0.4.x line, then graduated to ``jax.shard_map``;
* varying-type marking went ``lax.pvary`` (0.5/0.6 era) and then
  ``lax.pcast(..., to="varying")`` (0.9+, which auto-psums cotangents
  of unvaried inputs — the marker is what keeps gradients LOCAL so the
  step's one explicit ``pmean`` stays the only all-reduce). Pre-pvary
  shard_map has no varying-type tracking at all, so cotangents come
  back local already and the correct marker is the identity.

Product code must not pin any one spelling — these helpers resolve the
best available implementation at call time (cheap getattr probes, no
import-time jax dependency), so the same file runs on the 0.4.37
container, the 0.9 dev box, and whatever ships next.
"""

from __future__ import annotations


def resolve_shard_map():
    """The best available ``shard_map`` callable: ``jax.shard_map``
    when it exists, else ``jax.experimental.shard_map.shard_map``.
    Raises ``NotImplementedError`` only if neither exists (pre-0.4.3
    jax, below this repo's floor)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    try:
        from jax.experimental.shard_map import shard_map as sm
    except ImportError as e:                      # pragma: no cover
        raise NotImplementedError(
            f"this jax ({jax.__version__}) has neither jax.shard_map "
            "nor jax.experimental.shard_map — too old for the SPMD "
            "plane") from e
    return sm


def shard_map(f, **kwargs):
    """``jax.shard_map``-or-``jax.experimental.shard_map`` (resolved per
    call — cheap, and keeps this module import-safe without jax).
    Callers pass ``mesh``/``in_specs``/``out_specs`` as keywords, the
    signature both generations share.

    The replication-check toggle RENAMED between generations —
    ``check_rep`` (0.4.x experimental) became ``check_vma`` (jax with
    the varying-type system). Callers may pass either spelling; it is
    forwarded under whichever name this jax accepts (and dropped if the
    resolved shard_map has neither — the check simply stays at its
    default there)."""
    import inspect

    sm = resolve_shard_map()
    if "check_vma" in kwargs or "check_rep" in kwargs:
        val = kwargs.pop("check_vma", None)
        if "check_rep" in kwargs:
            val = kwargs.pop("check_rep")
        try:
            accepted = inspect.signature(sm).parameters
        except (TypeError, ValueError):     # pragma: no cover
            accepted = {}
        if "check_vma" in accepted:
            kwargs["check_vma"] = val
        elif "check_rep" in accepted:
            kwargs["check_rep"] = val
    return sm(f, **kwargs)


def axis_size(axis_name: str):
    """Static size of a mapped axis inside a ``shard_map``/``pmap`` body:
    ``lax.axis_size`` where it exists, else ``lax.psum(1, axis)`` — the
    pre-axis_size spelling (a static constant either way: the axis size
    is known at trace time)."""
    from jax import lax

    sz = getattr(lax, "axis_size", None)
    if sz is not None:
        return sz(axis_name)
    return lax.psum(1, axis_name)


def auto_interpret() -> bool:
    """Whether Pallas kernels should run in INTERPRET mode on this
    backend: True anywhere but a real TPU. THE one copy of the
    CPU-vs-TPU kernel dispatch decision — both ``ops.flash_attention``
    and ``ops.decode_attention`` resolve their ``interpret=None``
    default through here, so the two kernels can never drift on when
    the compiled Mosaic path engages (tier-1 CI runs everything in
    interpret mode on CPU; the compiled path is exercised by the
    TPU/multichip dryrun flow)."""
    import jax

    return jax.default_backend() != "tpu"


def pallas_tpu_compiler_params(**kwargs):
    """A Mosaic compiler-params object for ``pl.pallas_call`` — the
    class RENAMED between jax generations (``pltpu.TPUCompilerParams``
    on the 0.4.x line, ``pltpu.CompilerParams`` later). Callers pass
    the fields both generations share (``dimension_semantics=...``);
    this resolves whichever spelling the installed jax has, so the
    compiled (non-interpret) kernel path traces on every supported
    generation — interpret-mode CI never touches compiler params, which
    is exactly how a pinned spelling would rot undetected."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    if cls is None:                               # pragma: no cover
        import jax

        raise NotImplementedError(
            f"this jax ({jax.__version__}) has neither "
            "pltpu.CompilerParams nor pltpu.TPUCompilerParams")
    return cls(**kwargs)


def varying_axes(x):
    """The varying-manual-axes (vma) set of ``x``'s type on jax
    generations with the varying-type system (``jax.typeof`` + ``.vma``),
    else an empty frozenset — pre-vma jax (e.g. 0.4.37) tracks no
    replication types, so nothing varies as far as type checking goes."""
    import jax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", None) or frozenset()


def varying_marker_kind() -> str:
    """Which marker :func:`device_varying_marker` resolves to on this
    jax: ``"pcast"`` (0.9+), ``"pvary"`` (0.5/0.6 era), or
    ``"identity"`` (pre-pvary, e.g. 0.4.37 — no varying-type system, so
    there is nothing to mark).  Lets callers that *test* the marking
    construction skip where it cannot be built, without probing
    ``lax.pcast``/``lax.pvary`` themselves (that probe is exactly the
    compat drift SPMD101 flags)."""
    from jax import lax

    if getattr(lax, "pcast", None) is not None:
        return "pcast"
    if getattr(lax, "pvary", None) is not None:
        return "pvary"
    return "identity"


def device_varying_marker(axis_name: str):
    """A function marking an array device-varying over ``axis_name``
    inside a ``shard_map`` body — the knob that keeps cotangents of
    replicated inputs LOCAL (per-shard) instead of auto-psummed:

    * jax >= 0.9: ``lax.pcast(x, axis, to="varying")``;
    * pvary-era jax: ``lax.pvary(x, axis)``;
    * pre-pvary jax (e.g. 0.4.37): identity — old shard_map has no
      varying-type system, cotangents are already local.
    """
    from jax import lax

    kind = varying_marker_kind()
    if kind == "pcast":
        return lambda x: lax.pcast(x, axis_name, to="varying")
    if kind == "pvary":
        return lambda x: lax.pvary(x, axis_name)
    return lambda x: x
