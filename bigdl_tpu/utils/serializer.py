"""Structured module serialization — ``save_module`` / ``load_module``.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/serializer/
ModuleSerializer.scala`` + ``DataConverter.scala`` + ``bigdl.proto`` — a
versioned, reflection-driven, language-neutral module format, distinct from
the legacy Java-serialization ``Module.save`` (our pickle-based
``File.save``).

TPU-native redesign: the on-disk artifact is a zip holding

* ``spec.json``  — versioned topology: a flat object table (so shared
  modules / DAG nodes keep identity, exactly what the reference's
  weight-sharing semantics need) of whitelisted ``bigdl_tpu`` classes with
  JSON-encoded attributes, plus magic + format version;
* ``arrays.npz`` — every parameter / buffer array, referenced by index.

Unlike pickle, loading executes **no arbitrary code**: only classes that
resolve inside the ``bigdl_tpu`` package are instantiated (via
``cls.__new__`` + attribute restore, honoring ``__setstate__`` hooks), which
is the same safety property the reference gets from protobuf.
"""

from __future__ import annotations

import importlib
import io
import json
import os
import tempfile
import zipfile
from typing import Any, Dict, List

import numpy as np

MAGIC = "bigdl_tpu.module"
FORMAT_VERSION = 1

_ALLOWED_ROOT = "bigdl_tpu"


def _is_array(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return True
    # jax.Array without importing jax eagerly
    return type(x).__module__.startswith("jax") and hasattr(x, "__array__")


class _Encoder:
    def __init__(self) -> None:
        self.objs: List[Dict[str, Any]] = []
        self.obj_ids: Dict[int, int] = {}
        self.arrays: List[np.ndarray] = []
        # id(original array) → index, so aliased arrays (reference share()
        # semantics) keep identity across a round-trip; holding the original
        # in _array_refs keeps the ids valid for the encoder's lifetime
        self.array_ids: Dict[int, int] = {}
        self._array_refs: List[Any] = []

    def encode(self, x: Any) -> Any:
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        if isinstance(x, np.generic):  # numpy scalar
            return {"__npscalar__": [x.dtype.str, x.item()]}
        if _is_array(x):
            idx = self.array_ids.get(id(x))
            if idx is None:
                idx = len(self.arrays)
                self.arrays.append(np.asarray(x))
                self.array_ids[id(x)] = idx
                self._array_refs.append(x)
            return {"__array__": idx}
        if isinstance(x, (list, tuple)):
            tag = "__tuple__" if isinstance(x, tuple) else "__list__"
            return {tag: [self.encode(v) for v in x]}
        if isinstance(x, dict):
            items = [[self.encode(k), self.encode(v)] for k, v in x.items()]
            return {"__map__": items}
        cls = type(x)
        if cls.__module__.split(".")[0] == _ALLOWED_ROOT:
            return {"__obj__": self._encode_obj(x)}
        raise TypeError(
            f"save_module: cannot serialize {cls.__module__}.{cls.__name__}; "
            "only JSON scalars, arrays, containers and bigdl_tpu objects are "
            "supported"
        )

    def _encode_obj(self, x: Any) -> int:
        oid = self.obj_ids.get(id(x))
        if oid is not None:
            return oid
        oid = len(self.objs)
        self.obj_ids[id(x)] = oid
        entry: Dict[str, Any] = {
            "class": f"{type(x).__module__}:{type(x).__qualname__}",
        }
        self.objs.append(entry)  # reserve slot first: attrs may refer back
        state = x.__getstate__() if hasattr(x, "__getstate__") else None
        if state is None:  # object.__getstate__ returns None for empty state
            state = dict(getattr(x, "__dict__", {}))
        elif isinstance(state, tuple) and len(state) == 2:
            # py3.11+ object.__getstate__ for __slots__ classes:
            # (dict_state | None, slots_state | None)
            d, slots = state
            state = dict(d or {})
            state.update(slots or {})
        if not isinstance(state, dict):
            raise TypeError(
                f"save_module: {type(x).__qualname__}.__getstate__ returned "
                f"{type(state).__name__}; only dict state is supported"
            )
        entry["attrs"] = {k: self.encode(v) for k, v in state.items()}
        return oid


class _Decoder:
    def __init__(self, objs: List[Dict[str, Any]], arrays: Dict[str, np.ndarray]):
        self.spec_objs = objs
        self.arrays = arrays
        self.built: Dict[int, Any] = {}

    def decode(self, x: Any) -> Any:
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        if isinstance(x, dict):
            if "__npscalar__" in x:
                dt, v = x["__npscalar__"]
                return np.dtype(dt).type(v)
            if "__array__" in x:
                return self.arrays[f"a{x['__array__']}"]
            if "__list__" in x:
                return [self.decode(v) for v in x["__list__"]]
            if "__tuple__" in x:
                return tuple(self.decode(v) for v in x["__tuple__"])
            if "__map__" in x:
                return {self.decode(k): self.decode(v) for k, v in x["__map__"]}
            if "__obj__" in x:
                return self._decode_obj(x["__obj__"])
        raise ValueError(f"load_module: malformed spec node {x!r}")

    def _decode_obj(self, oid: int) -> Any:
        if oid in self.built:
            return self.built[oid]
        entry = self.spec_objs[oid]
        mod_name, _, qual = entry["class"].partition(":")
        if mod_name.split(".")[0] != _ALLOWED_ROOT or "." in qual:
            raise ValueError(
                f"load_module: refusing to instantiate {entry['class']!r}"
            )
        module = importlib.import_module(mod_name)
        cls = getattr(module, qual)
        obj = cls.__new__(cls)
        self.built[oid] = obj  # register before attrs: allow back-references
        attrs = {k: self.decode(v) for k, v in entry["attrs"].items()}
        if hasattr(obj, "__setstate__"):
            obj.__setstate__(attrs)
        else:
            for k, v in attrs.items():  # object.__setattr__ covers __slots__
                object.__setattr__(obj, k, v)
        return obj


def save_module(module, path: str, over_write: bool = False) -> None:
    """Serialize a module (topology + params + buffers) to ``path``."""
    if os.path.exists(path) and not over_write:
        raise FileExistsError(f"{path} exists (pass over_write=True)")
    module._materialize_params()  # weights only — grads aren't saved
    # params/state ride along inside the module's own attribute state
    # (AbstractModule.__getstate__ keeps them, drops grads/activations)
    enc = _Encoder()
    root = enc.encode(module)
    payload = {
        "magic": MAGIC,
        "version": FORMAT_VERSION,
        "root": root,
        "objects": enc.objs,
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, **{f"a{i}": a for i, a in enumerate(enc.arrays)})
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            with zipfile.ZipFile(f, "w", zipfile.ZIP_DEFLATED) as z:
                z.writestr("spec.json", json.dumps(payload))
                z.writestr("arrays.npz", buf.getvalue())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_module(path: str):
    """Load a module saved by :func:`save_module`."""
    with zipfile.ZipFile(path, "r") as z:
        payload = json.loads(z.read("spec.json"))
        arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
    if payload.get("magic") != MAGIC:
        raise ValueError(f"{path}: not a {MAGIC} file")
    if payload.get("version", 0) > FORMAT_VERSION:
        raise ValueError(
            f"{path}: format version {payload['version']} is newer than "
            f"supported {FORMAT_VERSION}"
        )
    dec = _Decoder(payload["objects"], arrays)
    module = dec.decode(payload["root"])
    module.grad_params = None
    module._ensure_params()
    return module
