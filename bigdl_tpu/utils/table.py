"""Table — Lua-style heterogeneous 1-based table.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/Table.scala`` —
the ``Activity`` for multi-input/multi-output layers and the state container
for optimization methods (``state("epoch")``, ``state("neval")``).

TPU-native note: inside jitted code plain pytrees (lists/dicts) are used;
``Table`` exists for API parity at the user surface and is registered as a
JAX pytree so it can cross jit boundaries when needed.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator


class Table:
    """Int-or-string keyed table; integer keys are 1-based like the reference."""

    def __init__(self, *elements: Any, **named: Any) -> None:
        self._data: Dict[Any, Any] = {}
        for i, el in enumerate(elements):
            self._data[i + 1] = el
        self._data.update(named)

    # -- element access ----------------------------------------------------

    def __getitem__(self, key: Any) -> Any:
        return self._data[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._data[key] = value

    def __call__(self, key: Any) -> Any:  # state("epoch") style access
        return self._data[key]

    def get(self, key: Any, default: Any = None) -> Any:
        return self._data.get(key, default)

    def get_or_update(self, key: Any, default: Any) -> Any:
        if key not in self._data:
            self._data[key] = default
        return self._data[key]

    def __contains__(self, key: Any) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def length(self) -> int:
        return len(self._data)

    def keys(self):
        return self._data.keys()

    def values(self):
        return self._data.values()

    def items(self):
        return self._data.items()

    def __iter__(self) -> Iterator[Any]:
        return iter(self._data.values())

    # -- mutation ----------------------------------------------------------

    def insert(self, value: Any) -> "Table":
        """Append at the next free integer index (1-based)."""
        i = 1
        while i in self._data:
            i += 1
        self._data[i] = value
        return self

    def remove(self, key: Any = None) -> Any:
        if key is None:
            key = max(k for k in self._data if isinstance(k, int))
        return self._data.pop(key, None)

    def update(self, other) -> "Table":
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self._data[k] = v
        return self

    def clear(self) -> "Table":
        self._data.clear()
        return self

    # -- conversion --------------------------------------------------------

    def to_list(self) -> list:
        n = len(self._data)
        return [self._data[i + 1] for i in range(n)]

    def to_dict(self) -> dict:
        return dict(self._data)

    @staticmethod
    def from_list(xs) -> "Table":
        return Table(*xs)

    # -- dunder ------------------------------------------------------------

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Table) and self._data == other._data

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}: {v!r}" for k, v in self._data.items())
        return f"T({{{inner}}})"


def T(*elements: Any, **named: Any) -> Table:
    """Constructor shorthand mirroring the reference's ``T()``."""
    return Table(*elements, **named)


def _table_flatten(t: Table):
    keys = sorted(t._data.keys(), key=lambda k: (isinstance(k, str), k))
    return [t._data[k] for k in keys], tuple(keys)


def _table_unflatten(keys, children) -> Table:
    t = Table()
    for k, v in zip(keys, children):
        t[k] = v
    return t


try:  # register as pytree so Tables can cross jit boundaries
    import jax.tree_util as _jtu

    _jtu.register_pytree_node(Table, _table_flatten, _table_unflatten)
except Exception:  # pragma: no cover
    pass
