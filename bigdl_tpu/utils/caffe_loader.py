"""CaffeLoader — import Caffe prototxt + caffemodel as a Graph.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/caffe/
CaffeLoader.scala`` + ``Converter.scala`` — parses a deploy ``prototxt``
(topology) and binary ``caffemodel`` (weights), converting each layer via a
per-type converter table into a BigDL ``Graph``.

TPU-native implementation notes: Caffe's NCHW / ``(out, in/g, kH, kW)``
conventions match this framework's core layers exactly, so blobs load with
no transposition. No ``caffe_pb2`` dependency exists in this image, so two
tiny self-contained parsers are included: a protobuf **text-format** parser
for prototxt and a protobuf **wire-format** decoder for the caffemodel's
``NetParameter`` subset (new-style ``layer`` only; field numbers from the
public caffe.proto).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf wire-format decoder (subset: varint, 64-bit, length-delimited,
# 32-bit). Returns {field_number: [raw values]}; submessages stay bytes.
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decode_message(buf: bytes) -> Dict[int, List[Any]]:
    fields: Dict[int, List[Any]] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:  # 64-bit
            val = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wtype == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:  # 32-bit
            val = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        fields.setdefault(fnum, []).append(val)
    return fields


def _floats(field_vals: List[Any]) -> np.ndarray:
    """Packed or unpacked repeated float."""
    out: List[float] = []
    for v in field_vals:
        if isinstance(v, bytes):  # packed
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
        else:
            out.append(float(v))
    return np.asarray(out, np.float32)


def _varints(field_vals: List[Any]) -> List[int]:
    out: List[int] = []
    for v in field_vals:
        if isinstance(v, bytes):  # packed
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(x)
        else:
            out.append(int(v))
    return out


def _blob_to_array(blob_bytes: bytes) -> np.ndarray:
    """BlobProto: shape=7 (BlobShape.dim=1), data=5, legacy num/c/h/w=1..4."""
    f = decode_message(blob_bytes)
    data = _floats(f.get(5, []))
    if 7 in f:
        dims = _varints(decode_message(f[7][0]).get(1, []))
    else:
        dims = [int(f.get(i, [1])[0]) for i in (1, 2, 3, 4)]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    return data.reshape(dims) if dims else data


def parse_caffemodel(path_or_bytes) -> Dict[str, List[np.ndarray]]:
    """caffemodel → {layer name: [blob arrays]} (new-style ``layer``=100)."""
    buf = path_or_bytes
    if isinstance(buf, str):
        with open(buf, "rb") as fh:
            buf = fh.read()
    net = decode_message(buf)
    out: Dict[str, List[np.ndarray]] = {}
    for layer_bytes in net.get(100, []):
        f = decode_message(layer_bytes)
        name = f.get(1, [b""])[0].decode()
        blobs = [_blob_to_array(b) for b in f.get(7, [])]
        if blobs:
            out[name] = blobs
    return out


# ---------------------------------------------------------------------------
# prototxt (protobuf text format) parser → nested dict-of-lists
# ---------------------------------------------------------------------------


def _tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
        elif c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in "{}:":
            tokens.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and text[j] != c:
                j += 1
            tokens.append(text[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n{}:#\"'":
                j += 1
            tokens.append(text[i:j])
            i = j
    return tokens


def _parse_value(tok: str):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    if tok in ("true", "True"):
        return True
    if tok in ("false", "False"):
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok  # enum name


def parse_prototxt(text: str) -> Dict[str, List[Any]]:
    """Text-format message → dict {field: [values]}; nested msgs are dicts."""
    tokens = _tokenize(text)
    pos = 0

    def parse_block() -> Dict[str, List[Any]]:
        nonlocal pos
        msg: Dict[str, List[Any]] = {}
        while pos < len(tokens) and tokens[pos] != "}":
            key = tokens[pos]
            pos += 1
            if tokens[pos] == ":":
                pos += 1
                msg.setdefault(key, []).append(_parse_value(tokens[pos]))
                pos += 1
            elif tokens[pos] == "{":
                pos += 1
                sub = parse_block()
                assert tokens[pos] == "}"
                pos += 1
                msg.setdefault(key, []).append(sub)
            else:
                raise ValueError(f"parse error near {tokens[pos - 1:pos + 2]}")
        return msg

    return parse_block()


# ---------------------------------------------------------------------------
# layer converters
# ---------------------------------------------------------------------------


def _one(msg: Dict, key: str, default=None):
    v = msg.get(key)
    return v[0] if v else default


def _conv_geometry(p: Dict) -> Tuple[int, int, int, int, int, int]:
    k = _one(p, "kernel_size")
    kh = _one(p, "kernel_h", k)
    kw = _one(p, "kernel_w", k)
    s = _one(p, "stride", 1)
    sh = _one(p, "stride_h", s)
    sw = _one(p, "stride_w", s)
    pd = _one(p, "pad", 0)
    ph = _one(p, "pad_h", pd)
    pw = _one(p, "pad_w", pd)
    return kw, kh, sw, sh, pw, ph


def load_caffe(prototxt, caffemodel=None, match_all: bool = True):
    """Build a :class:`Graph` from a deploy prototxt (+ optional weights).

    ``prototxt``: path or text. ``caffemodel``: path or bytes. Returns the
    Graph (reference ``Module.loadCaffeModel(defPath, modelPath)``).
    """
    from bigdl_tpu.nn import (
        CAddTable, CMulTable, Dropout, JoinTable, Linear, LogSoftMax, ReLU,
        Scale, Sigmoid, SoftMax, SpatialAveragePooling, SpatialBatchNormalization,
        SpatialConvolution, SpatialCrossMapLRN, SpatialMaxPooling, Tanh,
    )
    from bigdl_tpu.nn.graph import Graph, Input

    if isinstance(prototxt, str) and "\n" not in prototxt and prototxt.endswith(
            (".prototxt", ".txt")):
        with open(prototxt) as fh:
            prototxt = fh.read()
    net = parse_prototxt(prototxt)
    blobs = parse_caffemodel(caffemodel) if caffemodel is not None else {}

    value_nodes: Dict[str, Any] = {}
    graph_inputs: List[Any] = []

    # top-level "input:" declarations (deploy nets)
    for name in net.get("input", []):
        node = Input()
        graph_inputs.append(node)
        value_nodes[name] = node

    pending_weights: Dict[str, Tuple[Any, List[np.ndarray]]] = {}
    last_node = None

    for layer in net.get("layer", []):
        lname = _one(layer, "name", "")
        ltype = _one(layer, "type", "")
        bottoms = layer.get("bottom", [])
        tops = layer.get("top", [])
        lblobs = blobs.get(lname, [])

        if ltype == "Input":
            node = Input()
            graph_inputs.append(node)
            for t in tops:
                value_nodes[t] = node
            last_node = node
            continue

        mod, n_out = _convert_layer(
            ltype, layer, lblobs,
            dict(CAddTable=CAddTable, CMulTable=CMulTable, Dropout=Dropout,
                 JoinTable=JoinTable, Linear=Linear, LogSoftMax=LogSoftMax,
                 ReLU=ReLU, Scale=Scale, Sigmoid=Sigmoid, SoftMax=SoftMax,
                 SpatialAveragePooling=SpatialAveragePooling,
                 SpatialBatchNormalization=SpatialBatchNormalization,
                 SpatialConvolution=SpatialConvolution,
                 SpatialCrossMapLRN=SpatialCrossMapLRN,
                 SpatialMaxPooling=SpatialMaxPooling, Tanh=Tanh),
        )
        if mod is None:
            continue  # consumed structurally (e.g. train-only layers)
        mod.set_name(lname)
        preds = [value_nodes[b] for b in bottoms]
        node = mod.inputs(*preds)
        for t in tops:
            value_nodes[t] = node
        last_node = node
        if lblobs:
            pending_weights[lname] = (mod, lblobs)

    outputs = [last_node]
    g = Graph(graph_inputs if len(graph_inputs) > 1 else graph_inputs[0],
              outputs[0])
    g._ensure_params()
    _install_weights(g, pending_weights, match_all)
    return g


def _convert_layer(ltype: str, layer: Dict, lblobs, L) -> Tuple[Any, int]:
    p_conv = _one(layer, "convolution_param", {})
    if ltype == "Convolution":
        kw, kh, sw, sh, pw, ph = _conv_geometry(p_conv)
        n_out = _one(p_conv, "num_output")
        group = _one(p_conv, "group", 1)
        bias = bool(_one(p_conv, "bias_term", True))
        n_in = lblobs[0].shape[1] * group if lblobs else _one(
            p_conv, "_n_input", None)
        if n_in is None:
            raise ValueError(
                f"Convolution {_one(layer, 'name')}: input channels unknown "
                "(no caffemodel blobs; pass the caffemodel)")
        return L["SpatialConvolution"](
            int(n_in), int(n_out), kw, kh, sw, sh, pw, ph, n_group=group,
            with_bias=bias), n_out
    if ltype == "InnerProduct":
        p = _one(layer, "inner_product_param", {})
        n_out = _one(p, "num_output")
        bias = bool(_one(p, "bias_term", True))
        if not lblobs:
            raise ValueError("InnerProduct needs caffemodel blobs for sizing")
        n_in = lblobs[0].shape[-1]
        return L["Linear"](int(n_in), int(n_out), with_bias=bias), n_out
    if ltype == "Pooling":
        p = _one(layer, "pooling_param", {})
        pool = _one(p, "pool", "MAX")
        k = _one(p, "kernel_size", 2)
        kh, kw = _one(p, "kernel_h", k), _one(p, "kernel_w", k)
        s = _one(p, "stride", 1)
        sh, sw = _one(p, "stride_h", s), _one(p, "stride_w", s)
        pd = _one(p, "pad", 0)
        ph, pw = _one(p, "pad_h", pd), _one(p, "pad_w", pd)
        if _one(p, "global_pooling", False):
            return L["SpatialAveragePooling"](
                1, 1, 1, 1, global_pooling=True), None
        cls = L["SpatialMaxPooling"] if pool in ("MAX", 0) else L[
            "SpatialAveragePooling"]
        mod = cls(kw, kh, sw, sh, pw, ph)
        # caffe defaults to CEIL; round_mode FLOOR (=1) opts out
        if _one(p, "round_mode", "CEIL") in ("CEIL", 0):
            mod = mod.ceil()
        return mod, None
    if ltype == "ReLU":
        return L["ReLU"](), None
    if ltype == "TanH":
        return L["Tanh"](), None
    if ltype == "Sigmoid":
        return L["Sigmoid"](), None
    if ltype == "Softmax":
        return L["SoftMax"](), None
    if ltype == "Flatten":
        from bigdl_tpu.nn.shape_ops import Reshape

        fp = _one(layer, "flatten_param", {})
        if _one(fp, "axis", 1) != 1 or _one(fp, "end_axis", -1) != -1:
            raise NotImplementedError(
                "Flatten with non-default axis/end_axis is unsupported")
        return Reshape([-1], batch_mode=True), None
    if ltype == "AbsVal":
        from bigdl_tpu.nn.misc import Abs

        return Abs(), None
    if ltype == "Power":
        from bigdl_tpu.nn.misc import Power

        p = _one(layer, "power_param", {})
        # caffe Power = (shift + scale*x)^power — exactly our Power module
        return Power(float(_one(p, "power", 1.0)),
                     scale=float(_one(p, "scale", 1.0)),
                     shift=float(_one(p, "shift", 0.0))), None
    if ltype == "Dropout":
        p = _one(layer, "dropout_param", {})
        return L["Dropout"](float(_one(p, "dropout_ratio", 0.5))), None
    if ltype == "LRN":
        p = _one(layer, "lrn_param", {})
        return L["SpatialCrossMapLRN"](
            int(_one(p, "local_size", 5)), float(_one(p, "alpha", 1.0)),
            float(_one(p, "beta", 0.75)), float(_one(p, "k", 1.0))), None
    if ltype == "BatchNorm":
        p = _one(layer, "batch_norm_param", {})
        n = lblobs[0].shape[0] if lblobs else None
        if n is None:
            raise ValueError("BatchNorm needs caffemodel blobs for sizing")
        return L["SpatialBatchNormalization"](
            int(n), eps=float(_one(p, "eps", 1e-5)), affine=False), None
    if ltype == "Scale":
        p = _one(layer, "scale_param", {})
        n = lblobs[0].shape[0] if lblobs else None
        if n is None:
            raise ValueError("Scale needs caffemodel blobs for sizing")
        return L["Scale"]((int(n),)), None
    if ltype == "Concat":
        p = _one(layer, "concat_param", {})
        axis = int(_one(p, "axis", _one(p, "concat_dim", 1)))
        return L["JoinTable"](axis + 1, -1), None  # caffe axis incl batch
    if ltype == "Eltwise":
        p = _one(layer, "eltwise_param", {})
        op = _one(p, "operation", "SUM")
        if op in ("SUM", 1):
            return L["CAddTable"](), None
        if op in ("PROD", 0):
            return L["CMulTable"](), None
        raise NotImplementedError(f"Eltwise op {op}")
    if ltype == "Deconvolution":
        kw, kh, sw, sh, pw, ph = _conv_geometry(p_conv)
        n_out = int(_one(p_conv, "num_output"))
        group = int(_one(p_conv, "group", 1))
        bias = bool(_one(p_conv, "bias_term", True))
        if not lblobs:
            raise ValueError("Deconvolution needs caffemodel blobs for "
                             "sizing (weight blob is (in, out/g, kh, kw))")
        n_in = int(lblobs[0].shape[0])
        from bigdl_tpu.nn.conv import SpatialFullConvolution

        return SpatialFullConvolution(
            n_in, n_out, kw, kh, sw, sh, pw, ph, n_group=group,
            no_bias=not bias), n_out
    if ltype == "PReLU":
        p = _one(layer, "prelu_param", {})
        shared = bool(_one(p, "channel_shared", False))
        from bigdl_tpu.nn.activations import PReLU

        n = 0 if shared else int(np.asarray(lblobs[0]).size) if lblobs else 0
        return PReLU(n), None
    if ltype == "ELU":
        p = _one(layer, "elu_param", {})
        from bigdl_tpu.nn.activations import ELU

        return ELU(float(_one(p, "alpha", 1.0))), None
    if ltype == "Exp":
        from bigdl_tpu.nn.misc import Exp

        return Exp(), None
    if ltype == "Log":
        from bigdl_tpu.nn.misc import Log

        return Log(), None
    if ltype == "BNLL":
        from bigdl_tpu.nn.activations import SoftPlus

        return SoftPlus(), None
    if ltype == "Reshape":
        p = _one(layer, "reshape_param", {})
        shape = _one(p, "shape", {})
        dims = [int(d) for d in (shape.get("dim") or [])]
        from bigdl_tpu.nn.shape_ops import Reshape

        # caffe dim 0 = copy-from-bottom; the leading one is the batch dim
        if dims and dims[0] == 0:
            if 0 in dims[1:]:
                raise NotImplementedError(
                    "Caffe Reshape with non-leading dim:0 (copy-from-"
                    "bottom) needs the bottom shape; not supported")
            return Reshape([d for d in dims[1:]], batch_mode=True), None
        if 0 in dims:
            raise NotImplementedError(
                "Caffe Reshape with non-leading dim:0 (copy-from-bottom) "
                "needs the bottom shape; not supported")
        return Reshape(dims), None
    if ltype in ("Accuracy", "SoftmaxWithLoss", "Silence"):
        return None, None  # train/eval-only layers: skipped in deploy graphs
    raise NotImplementedError(f"Caffe layer type {ltype!r} unsupported")


def _install_weights(graph, pending, match_all: bool) -> None:
    """Copy caffemodel blobs into the built graph's param pytree."""
    for mod in graph._distinct_modules:
        entry = pending.get(mod.name)
        if entry is None:
            continue
        _, lblobs = entry
        key = graph._module_keys[id(mod)]
        p = graph.params.get(key, {})
        cls = type(mod).__name__
        if cls == "SpatialConvolution":
            p["weight"] = lblobs[0].astype(np.float32)
            if len(lblobs) > 1 and "bias" in p:
                p["bias"] = lblobs[1].astype(np.float32)
        elif cls == "SpatialFullConvolution":
            # caffe deconv blob is (in, out/g, kh, kw) — our layout exactly
            p["weight"] = lblobs[0].astype(np.float32)
            if len(lblobs) > 1 and "bias" in p:
                p["bias"] = lblobs[1].astype(np.float32)
        elif cls == "PReLU":
            p["weight"] = np.asarray(lblobs[0], np.float32).reshape(-1)
        elif cls == "Linear":
            p["weight"] = lblobs[0].reshape(p["weight"].shape).astype(np.float32)
            if len(lblobs) > 1 and "bias" in p:
                p["bias"] = lblobs[1].astype(np.float32)
        elif cls == "SpatialBatchNormalization":
            sf = float(lblobs[2].reshape(-1)[0]) if len(lblobs) > 2 else 1.0
            sf = 1.0 / sf if sf != 0 else 1.0
            st = graph.state.get(key, {})
            st["running_mean"] = (lblobs[0] * sf).astype(np.float32)
            st["running_var"] = (lblobs[1] * sf).astype(np.float32)
            graph.state[key] = st
        elif cls == "Scale":
            p["weight"] = lblobs[0].astype(np.float32)
            if len(lblobs) > 1:
                p["bias"] = lblobs[1].astype(np.float32)
        elif match_all:
            raise ValueError(
                f"caffemodel blobs for layer {mod.name!r} ({cls}) not matched")
        graph.params[key] = p
    graph.grad_params = None
    graph._ensure_params()


class CaffeLoader:
    """Reference-shaped facade (``Module.loadCaffeModel``)."""

    load = staticmethod(load_caffe)


# ---------------------------------------------------------------------------
# exporter (reference ``CaffePersister``) — wire-format encoder
# ---------------------------------------------------------------------------


from bigdl_tpu.utils.protowire import (  # noqa: E402 — exporter section
    field_bytes as _enc_ld_raw, tag as _enc_tag, varint as _enc_varint,
)


def _enc_ld(fnum: int, payload: bytes) -> bytes:
    return _enc_ld_raw(fnum, payload)


def _enc_blob(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr, np.float32)
    shape = b"".join(_enc_tag(1, 0) + _enc_varint(int(d)) for d in arr.shape)
    data = _enc_tag(5, 2) + _enc_varint(arr.size * 4) + struct.pack(
        f"<{arr.size}f", *arr.reshape(-1))
    return _enc_ld(7, shape) + data


def save_caffe(module, prototxt_path: str, caffemodel_path: str) -> None:
    """Export a module's weight-bearing layers as prototxt + caffemodel.

    Reference ``CaffePersister.persist``. Supported layer types mirror the
    importer's converter table (Convolution/InnerProduct/ReLU/Pooling/
    Softmax/...); layers outside Caffe's vocabulary raise.
    """
    from bigdl_tpu.nn.containers import Container, Sequential
    from bigdl_tpu.nn.graph import Graph

    module._materialize_params()
    lines = ['name: "bigdl_tpu_export"', 'input: "data"']
    blobs_bytes = b""
    prev_top = "data"

    def emit(mod, params):
        nonlocal blobs_bytes, prev_top
        cls = type(mod).__name__
        name = mod.name
        if cls == "SpatialConvolution":
            p = (f'layer {{ name: "{name}" type: "Convolution" '
                 f'bottom: "{prev_top}" top: "{name}"\n'
                 f'  convolution_param {{ num_output: {mod.n_output_plane} '
                 f'kernel_h: {mod.kernel_h} kernel_w: {mod.kernel_w} '
                 f'stride_h: {mod.stride_h} stride_w: {mod.stride_w} '
                 f'pad_h: {mod.pad_h} pad_w: {mod.pad_w} '
                 f'group: {mod.n_group} '
                 f'bias_term: {"true" if mod.with_bias else "false"} }} }}')
            lines.append(p)
            body = _enc_ld(1, name.encode())
            body += _enc_ld(7, _enc_blob(np.asarray(params["weight"])))
            if mod.with_bias:
                body += _enc_ld(7, _enc_blob(np.asarray(params["bias"])))
            blobs_bytes += _enc_ld(100, body)
            prev_top = name
        elif cls == "Linear":
            lines.append(
                f'layer {{ name: "{name}" type: "InnerProduct" '
                f'bottom: "{prev_top}" top: "{name}"\n'
                f'  inner_product_param {{ num_output: {mod.output_size} '
                f'bias_term: {"true" if mod.with_bias else "false"} }} }}')
            body = _enc_ld(1, name.encode())
            body += _enc_ld(7, _enc_blob(np.asarray(params["weight"])))
            if mod.with_bias:
                body += _enc_ld(7, _enc_blob(np.asarray(params["bias"])))
            blobs_bytes += _enc_ld(100, body)
            prev_top = name
        elif cls == "ReLU":
            lines.append(f'layer {{ name: "{name}" type: "ReLU" '
                         f'bottom: "{prev_top}" top: "{prev_top}" }}')
        elif cls == "Tanh":
            lines.append(f'layer {{ name: "{name}" type: "TanH" '
                         f'bottom: "{prev_top}" top: "{prev_top}" }}')
        elif cls == "Sigmoid":
            lines.append(f'layer {{ name: "{name}" type: "Sigmoid" '
                         f'bottom: "{prev_top}" top: "{prev_top}" }}')
        elif cls == "SoftMax":
            lines.append(f'layer {{ name: "{name}" type: "Softmax" '
                         f'bottom: "{prev_top}" top: "{name}" }}')
            prev_top = name
        elif cls in ("SpatialMaxPooling", "SpatialAveragePooling"):
            if mod.pad_h == -1 or mod.pad_w == -1:
                raise NotImplementedError(
                    f"pooling layer {name}: TF-style SAME padding (-1) has "
                    "no Caffe equivalent; set explicit pads before export")
            pool = "MAX" if cls == "SpatialMaxPooling" else "AVE"
            round_mode = "CEIL" if mod.ceil_mode else "FLOOR"
            lines.append(
                f'layer {{ name: "{name}" type: "Pooling" '
                f'bottom: "{prev_top}" top: "{name}"\n'
                f'  pooling_param {{ pool: {pool} kernel_h: {mod.kh} '
                f'kernel_w: {mod.kw} stride_h: {mod.dh} stride_w: {mod.dw} '
                f'pad_h: {mod.pad_h} pad_w: {mod.pad_w} '
                f'round_mode: {round_mode} }} }}')
            prev_top = name
        elif cls == "Dropout":
            lines.append(
                f'layer {{ name: "{name}" type: "Dropout" '
                f'bottom: "{prev_top}" top: "{prev_top}"\n'
                f'  dropout_param {{ dropout_ratio: {mod.p} }} }}')
        elif cls in ("Reshape", "View", "Identity"):
            pass  # shape plumbing has no caffe layer; consumers infer
        else:
            raise NotImplementedError(
                f"layer {cls} has no Caffe export mapping")

    def walk(mod, params):
        if isinstance(mod, Container) and type(mod).__name__ == "Sequential":
            for i, m in enumerate(mod.modules):
                walk(m, (params or {}).get(mod._child_key(i), {}))
        elif isinstance(mod, Graph):
            raise NotImplementedError(
                "Caffe export supports Sequential models (reference "
                "CaffePersister had the same linear-topology limitation)")
        else:
            emit(mod, params)

    walk(module, module.params)
    with open(prototxt_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with open(caffemodel_path, "wb") as f:
        f.write(blobs_bytes)


class CaffePersister:
    """Reference-shaped facade (``CaffePersister.persist``)."""

    persist = staticmethod(save_caffe)
