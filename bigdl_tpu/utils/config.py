"""Typed runtime configuration — the unified knob surface.

Reference (UNVERIFIED, SURVEY.md §0 / §5.6): the reference had THREE ad-hoc
config tiers — ``bigdl.*`` JVM system properties, SparkConf keys injected by
``Engine.createSparkConf``, and per-program scopt CLI parsers — with no
unified typed config. SURVEY.md §5.6 prescribes "one typed config object
(dataclass) + env/flag overlay, keeping the same knob names where sensible";
this module is that object.

Precedence (highest wins): explicit constructor/``replace`` values →
``BIGDL_*`` environment variables → defaults. The reference knob names map
1:1 (``bigdl.engineType`` → ``BIGDL_ENGINE_TYPE`` → ``engine_type``, …).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Optional

_ENV_PREFIX = "BIGDL_"


def _env_name(field_name: str) -> str:
    return _ENV_PREFIX + field_name.upper()


@dataclass
class BigDLConfig:
    """All runtime knobs in one place.

    | field | reference knob |
    |---|---|
    | ``engine_type``            | ``bigdl.engineType`` |
    | ``local_mode``             | ``bigdl.localMode`` |
    | ``node_number``            | ``bigdl.nodeNumber`` (executors) |
    | ``core_number``            | ``bigdl.coreNumber`` |
    | ``check_singleton``        | ``bigdl.check.singleton`` |
    | ``failure_retry_times``    | ``bigdl.failure.retryTimes`` |
    | ``failure_retry_interval`` | ``bigdl.failure.retryTimeInterval`` |
    | ``seed``                   | (RNG.setSeed) |
    | ``compute_dtype``          | — (TPU-native mixed precision) |
    | ``loss_scale``             | — (fp16 loss scaling) |
    """

    engine_type: str = "tpu"
    local_mode: Optional[bool] = None
    node_number: Optional[int] = None
    core_number: Optional[int] = None
    check_singleton: bool = False
    failure_retry_times: int = 5
    failure_retry_interval: float = 1.0
    seed: Optional[int] = None
    compute_dtype: Optional[str] = None
    loss_scale: float = 1.0

    @classmethod
    def from_env(cls, **overrides) -> "BigDLConfig":
        """Defaults ← BIGDL_* env ← explicit overrides."""
        kw = {}
        for f in dataclasses.fields(cls):
            env = os.environ.get(_env_name(f.name))
            if env is None:
                continue
            kw[f.name] = _parse(env, f.type)
        kw.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**kw)

    def replace(self, **kw) -> "BigDLConfig":
        return dataclasses.replace(self, **kw)

    # -- appliers ----------------------------------------------------------

    def apply_engine(self):
        """Push topology/engine knobs into the Engine singleton."""
        from bigdl_tpu.utils.engine import Engine

        Engine.init(node_number=self.node_number,
                    core_number=self.core_number,
                    engine_type=self.engine_type,
                    local_mode=self.local_mode)
        if self.seed is not None:
            from bigdl_tpu.utils.random_gen import RNG

            RNG.set_seed(self.seed)
        return Engine

    def apply_optimizer(self, optimizer):
        """Push training knobs onto an Optimizer (dtype, scaling, retry)."""
        if self.compute_dtype and self.compute_dtype != "fp32":
            optimizer.set_compute_dtype(self.compute_dtype)
        if self.loss_scale != 1.0:
            optimizer.set_loss_scale(self.loss_scale)
        optimizer.retry_times = self.failure_retry_times
        optimizer.retry_interval_s = self.failure_retry_interval
        return optimizer


def _parse(raw: str, ftype) -> object:
    t = str(ftype)
    if "bool" in t:
        return raw.strip().lower() in ("1", "true", "yes")
    if "int" in t:
        return int(raw)
    if "float" in t:
        return float(raw)
    return raw
