"""TensorflowLoader — import a frozen TensorFlow GraphDef as a Graph.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/tf/
TensorflowLoader.scala`` + ``.../utils/tf/loaders/*`` — parses a frozen
GraphDef, maps each node onto ``nn/ops`` modules, and wires a BigDL
``Graph``. Same architecture here: ``load_tf(path, inputs, outputs)`` walks
the GraphDef, lowers each node to a ``bigdl_tpu.nn.ops`` module (NHWC, no
layout shuffling — XLA assigns layouts), promotes Variables/Consts feeding
weight slots to trainable params, and returns a ``Graph`` whose forward
matches TF's execution of the same graph.

The protobuf parsing itself uses the installed ``tensorflow`` package (the
reference equally linked TF's protos); no TF runtime executes the model.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.nn import ops as O
from bigdl_tpu.nn.graph import Graph, Input, ModuleNode


def _attr(node, name, default=None):
    if name not in node.attr:
        return default
    return node.attr[name]


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util

    return np.asarray(tensor_util.MakeNdarray(node.attr["value"].tensor))


def _strides(node) -> List[int]:
    return list(node.attr["strides"].list.i)


def _padding(node) -> str:
    return node.attr["padding"].s.decode()


def _ksize(node) -> List[int]:
    return list(node.attr["ksize"].list.i)


# ops whose ONLY job is passthrough
_IDENTITY_OPS = {"Identity", "StopGradient", "CheckNumerics", "PlaceholderWithDefault"}

# table-returning ops: consumers address their results by port ("name:1");
# the loader inserts a SelectTable per referenced port
_MULTI_OUTPUT_OPS = {"Split", "SplitV", "Unpack", "Unstack", "TopKV2", "TopK",
                     "Switch", "RefSwitch", "While", "StatelessWhile",
                     "If", "StatelessIf"}

# v1 control-flow structural ops (reference nn/ops control flow — SURVEY
# §2.2); consumed by the while-frame extractor / cond pattern-matcher below
_CONTROL_FLOW_OPS = {"Enter", "RefEnter", "Merge", "RefMerge", "Switch",
                     "RefSwitch", "Exit", "RefExit", "NextIteration",
                     "RefNextIteration", "LoopCond"}

# weight-slot positions per op: input indices that, when fed by a Const,
# should become trainable ParameterOps rather than frozen ConstOps
_TRAINABLE_SLOTS = {
    "Conv2D": {1},
    "DepthwiseConv2dNative": {1},
    "MatMul": {1},
    "BiasAdd": {1},
    "FusedBatchNorm": {1, 2},
    "FusedBatchNormV3": {1, 2},
}


def load_tf(graph_def_or_path, inputs: Sequence[str], outputs: Sequence[str],
            generated_backward: bool = True) -> Graph:
    """Build a :class:`Graph` from a frozen GraphDef.

    ``inputs``/``outputs``: TF node names (``"x"`` or ``"scope/x:N"``).
    Multi-output ops (Split/SplitV/Unpack/TopK) are addressed by their port
    suffix — consumers and graph outputs get a per-port ``SelectTable``.
    """
    gd = _load_graph_def(graph_def_or_path)
    nodes: Dict[str, object] = {n.name: n for n in gd.node}
    strip = lambda name: name.split(":")[0].lstrip("^")
    input_names = [strip(n) for n in inputs]
    output_names = [strip(n) for n in outputs]

    # control flow: v1 while frames (Enter/Merge/Switch/Exit/NextIteration/
    # LoopCond) collapse to lax.while_loop; v2 functional While/If use the
    # FunctionDef library; v1 cond Switch/Merge pairs lower to select
    fns = ({f.signature.name: f for f in gd.library.function}
           if gd.HasField("library") else {})
    frames = _extract_while_frames(nodes)
    evaluator = _GraphEval(nodes, fns, frames)

    built: Dict[str, ModuleNode] = {}
    graph_inputs: List[ModuleNode] = []

    def const_feed(name: str, consumer_op: str, slot: int) -> ModuleNode:
        node = nodes[name]
        value = _const_value(node)
        trainable = slot in _TRAINABLE_SLOTS.get(consumer_op, set())
        # a rank-1 const added/subtracted is a bias in disguise (TF lowers
        # `matmul(x, w) + b` to AddV2, not BiasAdd) — keep it trainable
        if consumer_op in ("Add", "AddV2", "Sub") and value.ndim == 1:
            trainable = True
        mod = O.ParameterOp(value) if trainable else O.ConstOp(value)
        mod.set_name(name)
        # constants have no graph predecessors: hang them off a shared
        # zero-input — our Graph requires every node reachable from inputs,
        # so constants attach to the first real input node as a dummy dep
        return mod

    port_nodes: Dict[tuple, ModuleNode] = {}

    def build_port(name: str, port: int) -> ModuleNode:
        base = build(name)
        if nodes[strip(name)].op not in _MULTI_OUTPUT_OPS:
            return base
        key = (strip(name), port)
        if key not in port_nodes:
            from bigdl_tpu.nn import SelectTable

            sel = SelectTable(port + 1)  # 1-based
            sel.set_name(f"{strip(name)}:{port}")
            port_nodes[key] = sel.inputs(base)
        return port_nodes[key]

    def build(name: str) -> ModuleNode:
        name = strip(name)
        if name in built:
            return built[name]
        node = nodes[name]
        op = node.op

        if name in input_names:
            mn = Input()
            graph_inputs.append(mn)
            built[name] = mn
            return mn

        if op in ("Placeholder",):
            raise ValueError(
                f"Placeholder {name!r} is not listed in inputs={input_names}")

        if op in _IDENTITY_OPS:
            src = node.input[0]
            src_port = int(src.split(":")[1]) if ":" in src else 0
            mn = build_port(strip(src), src_port)
            built[name] = mn
            return mn

        if op in ("Exit", "RefExit"):
            if name not in frames:
                raise NotImplementedError(
                    f"Exit {name!r} reachable from the requested outputs "
                    "but its while frame could not be extracted (pruned or "
                    "malformed v1 loop)")
            mn = build_frame_exit(node)
            built[name] = mn
            return mn

        if op in ("Merge", "RefMerge"):
            mn = build_cond_merge(node)
            built[name] = mn
            return mn

        if op in ("While", "StatelessWhile"):
            cond_fn, body_fn = _function_while_fns(node, fns)
            mod = O.TFWhile(cond_fn, body_fn, n_vars=len(node.input))
            mod.set_name(name)
            preds = [build_operand(inp, op) for inp in node.input
                     if not inp.startswith("^")]
            mn = mod.inputs(*preds)
            built[name] = mn
            return mn

        if op in ("If", "StatelessIf"):
            then_fn, else_fn, n_out = _function_if_fns(node, fns)
            mod = O.TFCond(then_fn, else_fn, n_out)
            mod.set_name(name)
            preds = [build_operand(inp, op) for inp in node.input
                     if not inp.startswith("^")]
            mn = mod.inputs(*preds)
            built[name] = mn
            return mn

        if op == "Const":
            raise ValueError(
                f"Const {name!r} used outside a recognized operand slot")

        preds: List[ModuleNode] = []
        slot = 0
        for inp in node.input:
            if inp.startswith("^"):
                continue  # control edge
            preds.append(build_operand(inp, op, slot=slot))
            slot += 1

        mod = _lower(node)
        mod.set_name(name)
        mn = mod.inputs(*preds)
        built[name] = mn
        return mn

    def build_operand(ref: str, consumer_op: str, slot: int = -1) -> ModuleNode:
        """Resolve one operand ref ("name", "name:port"): Const sources
        become (anchored) ConstOp/ParameterOp nodes, everything else builds
        through the DAG."""
        iname = strip(ref)
        port = int(ref.split(":")[1]) if ":" in ref else 0
        src = nodes[iname]
        seen = set()
        while src.op in _IDENTITY_OPS and src.input:
            if src.name in seen:
                break
            seen.add(src.name)
            src = nodes[strip(src.input[0])]
        if src.op == "Const" and iname not in input_names:
            cmod = const_feed(src.name, consumer_op, slot)
            anchor = graph_inputs[0] if graph_inputs else build(input_names[0])
            return cmod.inputs(anchor)
        return build_port(iname, port)

    def build_frame_exit(exit_node) -> ModuleNode:
        """v1 while frame → ONE TFWhile (lax.while_loop) node; each Exit is
        a SelectTable port on it."""
        fr = frames[exit_node.name]
        key = ("__frame__", fr.frame_name)
        if key not in port_nodes:
            mod = fr.make_module(evaluator)
            mod.set_name(f"{fr.frame_name}/while")
            preds = [build_operand(v["enter"].input[0], "Enter")
                     for v in fr.vars]
            preds += [build_operand(e.input[0], "Enter")
                      for e in fr.const_enters]
            port_nodes[key] = mod.inputs(*preds)
        idx = fr.exit_index(exit_node.name)
        pkey = ("__frame_exit__", fr.frame_name, idx)
        if pkey not in port_nodes:
            from bigdl_tpu.nn import SelectTable

            sel = SelectTable(idx + 1)  # 1-based
            sel.set_name(exit_node.name)
            port_nodes[pkey] = sel.inputs(port_nodes[key])
        return port_nodes[pkey]

    def build_cond_merge(merge_node) -> ModuleNode:
        """v1 cond: Merge(false_branch, true_branch) → select on the
        controlling Switch predicate (compute-both-branches lowering)."""
        refs = [i for i in merge_node.input if not i.startswith("^")]
        if any(nodes[strip(r)].op in ("Enter", "RefEnter") for r in refs):
            raise NotImplementedError(
                f"Merge {merge_node.name!r} belongs to a while frame but "
                "was reached outside frame extraction")
        if len(refs) != 2:
            raise NotImplementedError(
                f"cond Merge {merge_node.name!r} with {len(refs)} branches")
        # the controlling predicate is the one BOTH branches are gated by,
        # with opposite ports — nested conds contribute their inner
        # predicate to one branch only, so first-Switch-found would pick
        # the wrong gate
        traces = [set(_trace_all_switches(nodes, r)) for r in refs]
        pairs = {
            pred: b0
            for (b0, pred) in traces[0]
            if (1 - b0, pred) in traces[1] and (b0, pred) not in traces[1]
        }
        if not pairs:
            raise NotImplementedError(
                f"cond Merge {merge_node.name!r}: no predicate gates both "
                "branches with opposite ports")
        if len(pairs) > 1:
            raise NotImplementedError(
                f"cond Merge {merge_node.name!r}: ambiguous controlling "
                f"predicates {sorted(pairs)}")
        (pred_ref, b0), = pairs.items()
        false_ref = refs[0] if b0 == 0 else refs[1]
        true_ref = refs[1] if b0 == 0 else refs[0]
        mod = O.CondMerge()
        mod.set_name(merge_node.name)
        return mod.inputs(
            build_operand(false_ref, "Merge"),
            build_operand(true_ref, "Merge"),
            build_operand(pred_ref, "Merge"),
        )

    # roots first so const anchoring has an input available
    for n in input_names:
        build(n)
    out_nodes = []
    for n in outputs:
        port = int(str(n).split(":")[1]) if ":" in str(n) else 0
        out_nodes.append(build_port(strip(str(n)), port))
    g = Graph(graph_inputs if len(graph_inputs) > 1 else graph_inputs[0],
              out_nodes if len(out_nodes) > 1 else out_nodes[0])
    return g


def _load_graph_def(graph_def_or_path):
    if isinstance(graph_def_or_path, (str, bytes)) and not isinstance(
            graph_def_or_path, bytes):
        from tensorflow.core.framework import graph_pb2

        gd = graph_pb2.GraphDef()
        with open(graph_def_or_path, "rb") as f:
            gd.ParseFromString(f.read())
        return gd
    return graph_def_or_path  # already a GraphDef


# -- control-flow machinery ---------------------------------------------------

def _split_ref(ref: str):
    """Tensor ref → (node_name, port). Handles "name", "name:1" and the
    FunctionDef form "name:output_name:k"."""
    ref = ref.lstrip("^")
    parts = ref.split(":")
    if len(parts) == 1:
        return parts[0], 0
    if len(parts) == 2:
        return parts[0], int(parts[1]) if parts[1].isdigit() else 0
    return parts[0], int(parts[-1])


def _trace_all_switches(nodes, ref, out=None, seen=None, _depth=0):
    """Walk a cond branch backwards collecting every (port, predicate_ref)
    of Switches crossed. v1 cond creates a SEPARATE Switch per captured
    tensor, all sharing one predicate — so gating is identified by
    predicate, not switch identity. Traversal continues THROUGH a Switch's
    data input (nested conds stack gates) and follows control edges
    (const-only branches are anchored by a control dep on the branch's
    switch pivot)."""
    if out is None:
        out, seen = [], set()
    if _depth > 512:
        return out
    name, port = _split_ref(ref)
    if (name, port) in seen:
        return out
    seen.add((name, port))
    node = nodes.get(name)
    if node is None:
        return out
    if node.op in ("Switch", "RefSwitch"):
        out.append((port, _resolve_identity(nodes, node.input[1])))
        _trace_all_switches(nodes, node.input[0], out, seen, _depth + 1)
        return out
    for i in node.input:
        _trace_all_switches(nodes, i, out, seen, _depth + 1)
    return out


def _resolve_identity(nodes, ref: str) -> str:
    """Canonicalize a ref through Identity chains (v1 cond routes the same
    predicate both directly and via a ``pred_id`` Identity)."""
    seen = set()
    while True:
        name, port = _split_ref(ref)
        node = nodes.get(name)
        if node is None or node.op not in _IDENTITY_OPS or not node.input \
                or name in seen:
            return f"{name}:{port}" if port else name
        seen.add(name)
        ref = node.input[0]


def _extract_while_frames(nodes):
    """Group v1 Enter nodes by frame_name and resolve each frame's loop
    structure; returns {exit_node_name: _WhileFrame}."""
    by_frame: Dict[str, list] = {}
    for n in nodes.values():
        if n.op in ("Enter", "RefEnter"):
            by_frame.setdefault(
                n.attr["frame_name"].s.decode(), []).append(n)
    consumers: Dict[str, list] = {}
    if by_frame:
        for n in nodes.values():
            for i in n.input:
                iname, _ = _split_ref(i)
                consumers.setdefault(iname, []).append(n)
    out: Dict[str, "_WhileFrame"] = {}
    for fname, enters in by_frame.items():
        try:
            fr = _WhileFrame(fname, enters, nodes, consumers)
        except NotImplementedError:
            # dead / freeze-pruned frame (e.g. leftover training control
            # flow): tolerate at load time — it only matters if one of its
            # Exits is actually reachable from the requested outputs, and
            # then build() fails loudly on the unmatched Exit
            continue
        for v in fr.vars:
            if v["exit"] is not None:
                out[v["exit"].name] = fr
    return out


class _WhileFrame:
    """One v1 while frame: per loop var the Enter→Merge→Switch→(Exit,
    body→NextIteration) diamond, plus loop-invariant constant Enters."""

    def __init__(self, frame_name, enters, nodes, consumers):
        self.frame_name = frame_name
        self.const_enters = [e for e in enters if e.attr["is_constant"].b]
        self.vars = []
        loopcond = None
        for e in enters:
            if e.attr["is_constant"].b:
                continue
            merge = next((c for c in consumers.get(e.name, ())
                          if c.op in ("Merge", "RefMerge")), None)
            if merge is None:
                raise NotImplementedError(
                    f"while frame {frame_name!r}: Enter {e.name!r} "
                    "has no Merge consumer")
            switch = next((c for c in consumers.get(merge.name, ())
                           if c.op in ("Switch", "RefSwitch")), None)
            if switch is None:
                raise NotImplementedError(
                    f"while frame {frame_name!r}: Merge {merge.name!r} "
                    "has no Switch consumer")
            exit_ = next((c for c in consumers.get(switch.name, ())
                          if c.op in ("Exit", "RefExit")), None)
            ni = nodes[_split_ref(merge.input[1])[0]]
            self.vars.append({"enter": e, "merge": merge, "switch": switch,
                              "exit": exit_, "next": ni})
            if loopcond is None:
                loopcond = nodes[_split_ref(switch.input[1])[0]]
        if loopcond is None or loopcond.op != "LoopCond":
            raise NotImplementedError(
                f"while frame {frame_name!r}: no LoopCond found")
        self.loopcond = loopcond

    def exit_index(self, exit_name: str) -> int:
        for i, v in enumerate(self.vars):
            if v["exit"] is not None and v["exit"].name == exit_name:
                return i
        raise KeyError(exit_name)

    def make_module(self, evaluator: "_GraphEval"):
        """Build the TFWhile module: cond evaluates the LoopCond predicate
        subgraph with loop vars fed at the Merges; body evaluates the
        NextIteration inputs with loop vars fed at Switch:1."""
        import jax.numpy as jnp

        cond_target = self.loopcond.input[0]
        body_targets = [v["next"].input[0] for v in self.vars]
        merges = [v["merge"].name for v in self.vars]
        switches = [v["switch"].name for v in self.vars]
        const_names = [e.name for e in self.const_enters]

        def feeds_for(carry, consts, keys):
            feeds = dict(zip(keys, carry))
            feeds.update(zip(const_names, consts))
            return feeds

        def cond_fn(carry, consts):
            (pred,) = evaluator.eval(
                [cond_target], feeds_for(carry, consts, merges))
            return jnp.asarray(pred).reshape(())

        def body_fn(carry, consts):
            outs = evaluator.eval(
                body_targets,
                feeds_for(carry, consts, [f"{s}:1" for s in switches]))
            # lax.while_loop needs a dtype-stable carry (TF guarantees
            # loop-var dtypes; weak-typed consts would otherwise drift)
            return tuple(jnp.asarray(o).astype(c.dtype)
                         for o, c in zip(outs, carry))

        return O.TFWhile(cond_fn, body_fn, n_vars=len(self.vars),
                         n_consts=len(self.const_enters))


# FunctionDef multi-output ops: output_arg name → port base (the common
# cases; single-output ops resolve to port 0 automatically)
_FN_OUTPUT_NAMES = {
    "Switch": ("output_false", "output_true"),
    "TopKV2": ("values", "indices"),
    "TopK": ("values", "indices"),
}


class _GraphEval:
    """Functional interpreter for a GraphDef/FunctionDef node set — reuses
    the ``_lower`` op table so control-flow bodies execute the exact same
    lowering as the surrounding Graph. Used to build lax.while_loop /
    lax.cond callables for TFWhile/TFCond.

    Limitation: Consts INSIDE a control-flow body (e.g. weights of a
    MatMul in a loop) import as frozen values, not trainable ParameterOps
    — the loop is one opaque module to the surrounding Graph. Fine-tuning
    reaches everything outside control flow, matching the reference's
    frozen-import scope."""

    def __init__(self, nodes, fns, frames):
        self.nodes = nodes
        self.fns = fns or {}
        self.frames = frames or {}

    def eval(self, targets, feeds):
        env = dict(feeds)

        def get(ref):
            name, port = _split_ref(ref)
            parts = ref.lstrip("^").split(":")
            if len(parts) == 3 and not parts[1].isdigit():
                node = self.nodes.get(name)
                if node is not None and node.op in _FN_OUTPUT_NAMES:
                    base = _FN_OUTPUT_NAMES[node.op].index(parts[1])
                    port = base + int(parts[2])
            key = f"{name}:{port}"
            if key in env:
                return env[key]
            if port == 0 and name in env:
                return env[name]
            out = self._node(self.nodes[name], get)
            if isinstance(out, (list, tuple)):
                for i, v in enumerate(out):
                    env[f"{name}:{i}"] = v
                return out[port]
            env[name] = out
            return out

        return [get(t) for t in targets]

    def _node(self, node, get):
        op = node.op
        if op == "Const":
            # plain numpy, NOT jnp: inside a while_loop/cond trace
            # jnp.asarray stages the constant as a tracer, which breaks
            # ops needing static operands (Gather axis, Reshape shape, …)
            return _const_value(node)
        if op in _IDENTITY_OPS or op in (
                "Enter", "RefEnter", "NextIteration", "RefNextIteration",
                "LoopCond", "Exit", "RefExit"):
            # inside an extracted frame these are pass-through; a NESTED
            # frame's Exit evaluates the inner loop recursively
            if op in ("Exit", "RefExit") and node.name in self.frames:
                fr = self.frames[node.name]
                mod = fr.make_module(self)
                ins = [get(v["enter"].input[0]) for v in fr.vars]
                ins += [get(e.input[0]) for e in fr.const_enters]
                out, _ = mod.apply({}, ins)
                return out[fr.exit_index(node.name)]
            return get(node.input[0])
        if op in ("While", "StatelessWhile"):
            cond_fn, body_fn = _function_while_fns(node, self.fns)
            ins = [get(i) for i in node.input if not i.startswith("^")]
            out, _ = O.TFWhile(cond_fn, body_fn, len(ins)).apply({}, ins)
            return out
        if op in ("If", "StatelessIf"):
            then_fn, else_fn, n_out = _function_if_fns(node, self.fns)
            ins = [get(i) for i in node.input if not i.startswith("^")]
            out, _ = O.TFCond(then_fn, else_fn, n_out).apply({}, ins)
            return out
        if op == "Merge":
            raise NotImplementedError(
                f"Merge {node.name!r} reached by the subgraph interpreter "
                "(cond-in-loop-body is not supported)")
        ins = [get(i) for i in node.input if not i.startswith("^")]
        mod = _lower(node)
        out, _ = mod.apply({}, ins if len(ins) != 1 else ins[0], None)
        return out


def _function_eval(fdef, fns):
    """FunctionDef → callable(args_tuple) -> outputs tuple."""
    nodes = {n.name: n for n in fdef.node_def}
    arg_names = [a.name for a in fdef.signature.input_arg]
    targets = [fdef.ret[a.name] for a in fdef.signature.output_arg]
    ev = _GraphEval(nodes, fns, {})

    def run(args):
        feeds = dict(zip(arg_names, args))
        return tuple(ev.eval(targets, feeds))

    return run


def _function_while_fns(node, fns):
    """v2 functional While: cond/body FunctionDefs → (cond_fn, body_fn)
    with the TFWhile (carry, consts) signature (no consts — v2 carries
    invariants through the loop vars)."""
    import jax.numpy as jnp

    cond_run = _function_eval(fns[node.attr["cond"].func.name], fns)
    body_run = _function_eval(fns[node.attr["body"].func.name], fns)

    def cond_fn(carry, consts):
        return jnp.asarray(cond_run(carry)[0]).reshape(())

    def body_fn(carry, consts):
        outs = body_run(carry)
        return tuple(jnp.asarray(o).astype(c.dtype)
                     for o, c in zip(outs, carry))

    return cond_fn, body_fn


def _function_if_fns(node, fns):
    """v2 functional If: then/else FunctionDefs → branch callables."""
    import jax.numpy as jnp

    then_f = fns[node.attr["then_branch"].func.name]
    else_f = fns[node.attr["else_branch"].func.name]
    then_run = _function_eval(then_f, fns)
    else_run = _function_eval(else_f, fns)
    n_out = len(then_f.signature.output_arg)

    def mk(run):
        def branch(args):
            outs = run(args)
            return tuple(jnp.asarray(o) for o in outs)
        return branch

    return mk(then_run), mk(else_run), n_out


def _lower(node):
    """GraphDef node → nn.ops module (the loaders/* table)."""
    op = node.op
    if op == "Conv2D":
        return O.Conv2D(_strides(node), _padding(node))
    if op == "DepthwiseConv2dNative":
        return O.DepthwiseConv2dNative(_strides(node), _padding(node))
    if op == "BiasAdd":
        return O.BiasAdd()
    if op == "MatMul":
        return O.MatMul(node.attr["transpose_a"].b, node.attr["transpose_b"].b)
    if op == "MaxPool":
        return O.MaxPool(_ksize(node), _strides(node), _padding(node))
    if op == "AvgPool":
        return O.AvgPool(_ksize(node), _strides(node), _padding(node))
    if op in ("FusedBatchNorm", "FusedBatchNormV3"):
        eps = node.attr["epsilon"].f or 1e-3
        return O.FusedBatchNorm(eps)
    if op == "Reshape":
        return O.Reshape()
    if op == "Squeeze":
        dims = list(node.attr["squeeze_dims"].list.i)
        return O.Squeeze(dims or None)
    if op == "ExpandDims":
        return O.ExpandDims()
    if op == "ConcatV2":
        return O.ConcatV2()
    if op == "Pad":
        return O.Pad()
    if op == "PadV2":
        return O.PadV2()
    if op == "MirrorPad":
        return O.MirrorPad(node.attr["mode"].s.decode())
    if op == "ResizeBilinear":
        return O.ResizeBilinear(
            node.attr["align_corners"].b,
            node.attr["half_pixel_centers"].b
            if "half_pixel_centers" in node.attr else False)
    if op == "ResizeNearestNeighbor":
        return O.ResizeNearestNeighbor(
            node.attr["align_corners"].b,
            node.attr["half_pixel_centers"].b
            if "half_pixel_centers" in node.attr else False)
    if op == "SpaceToBatchND":
        return O.SpaceToBatchND()
    if op == "BatchToSpaceND":
        return O.BatchToSpaceND()
    if op == "Rank":
        return O.RankOp()
    if op == "Size":
        return O.SizeOp()
    if op in ("Sin", "Cos", "Tan", "Asin", "Acos", "Atan", "Sinh", "Cosh",
              "Log1p", "Expm1", "IsNan", "IsInf", "IsFinite"):
        return getattr(O, op)()
    if op == "LRN":
        # presence checks, not truthiness: zero-valued attrs are legal
        return O.LRN(
            node.attr["depth_radius"].i if "depth_radius" in node.attr else 5,
            node.attr["bias"].f if "bias" in node.attr else 1.0,
            node.attr["alpha"].f if "alpha" in node.attr else 1.0,
            node.attr["beta"].f if "beta" in node.attr else 0.5)
    if op == "Mean":
        return O.Mean(node.attr["keep_dims"].b)
    if op in ("Add", "AddV2"):
        return O.Add()
    if op == "Sub":
        return O.Sub()
    if op == "Mul":
        return O.Mul()
    if op == "RealDiv":
        return O.RealDiv()
    if op == "Maximum":
        return O.Maximum()
    if op == "Rsqrt":
        return O.Rsqrt()
    if op == "AddN":
        from bigdl_tpu.nn.shape_ops import CAddTable

        return CAddTable()
    if op == "Neg":
        from bigdl_tpu.nn.layers_extra import Negative

        return Negative()
    if op == "Softplus":
        from bigdl_tpu.nn.activations import SoftPlus

        return SoftPlus()
    if op == "LeakyRelu":
        from bigdl_tpu.nn.activations import LeakyReLU

        alpha = (node.attr["alpha"].f if "alpha" in node.attr
                 else 0.2)  # 0.0 is a valid (plain-ReLU) alpha
        return LeakyReLU(alpha)
    if op == "Exp":
        from bigdl_tpu.nn.misc import Exp

        return Exp()
    if op == "Log":
        from bigdl_tpu.nn.misc import Log

        return Log()
    if op == "Sqrt":
        from bigdl_tpu.nn.misc import Sqrt

        return Sqrt()
    if op == "Square":
        from bigdl_tpu.nn.misc import Square

        return Square()
    if op == "Softmax":
        return O.Softmax()
    if op == "Relu":
        from bigdl_tpu.nn.activations import ReLU

        return ReLU()
    if op == "Relu6":
        from bigdl_tpu.nn.activations import ReLU6

        return ReLU6()
    if op == "Tanh":
        from bigdl_tpu.nn.activations import Tanh

        return Tanh()
    if op == "Sigmoid":
        from bigdl_tpu.nn.activations import Sigmoid

        return Sigmoid()
    if op == "Minimum":
        return O.Minimum()
    if op == "Pow":
        return O.Pow()
    if op == "FloorDiv":
        return O.FloorDiv()
    if op == "FloorMod":
        return O.FloorMod()
    if op == "SquaredDifference":
        return O.SquaredDifference()
    if op == "Greater":
        return O.Greater()
    if op == "GreaterEqual":
        return O.GreaterEqual()
    if op == "Less":
        return O.Less()
    if op == "LessEqual":
        return O.LessEqual()
    if op == "Equal":
        return O.Equal()
    if op == "NotEqual":
        return O.NotEqual()
    if op == "LogicalAnd":
        return O.LogicalAnd()
    if op == "LogicalOr":
        return O.LogicalOr()
    if op == "LogicalNot":
        return O.LogicalNot()
    if op == "Abs":
        return O.Abs()
    if op == "Floor":
        return O.Floor()
    if op == "Ceil":
        return O.Ceil()
    if op == "Round":
        return O.Round()
    if op == "Sign":
        return O.Sign()
    if op == "Elu":
        return O.Elu()
    if op == "Selu":
        return O.Selu()
    if op == "Erf":
        return O.Erf()
    if op == "Reciprocal":
        return O.Reciprocal()
    if op == "Cast":
        return O.Cast(_np_dtype(node.attr["DstT"].type))
    if op == "Transpose":
        return O.Transpose()
    if op == "Tile":
        return O.TileOp()
    if op == "Slice":
        return O.SliceOp()
    if op == "StridedSlice":
        return O.StridedSlice(node.attr["begin_mask"].i,
                              node.attr["end_mask"].i,
                              node.attr["shrink_axis_mask"].i,
                              node.attr["new_axis_mask"].i,
                              node.attr["ellipsis_mask"].i)
    if op in ("Pack", "Stack"):
        return O.PackOp(node.attr["axis"].i)
    if op in ("Unpack", "Unstack"):
        return O.Unpack(node.attr["axis"].i, node.attr["num"].i or None)
    if op == "Split":
        return O.SplitOp(node.attr["num_split"].i)
    if op == "SplitV":
        return O.SplitV()
    if op == "Fill":
        return O.Fill()
    if op in ("Select", "SelectV2"):
        return O.Select()
    if op == "ClipByValue":
        return O.ClipByValue()
    if op == "Sum":
        return O.Sum(node.attr["keep_dims"].b)
    if op == "Max":
        return O.Max(node.attr["keep_dims"].b)
    if op == "Min":
        return O.Min(node.attr["keep_dims"].b)
    if op == "Prod":
        return O.Prod(node.attr["keep_dims"].b)
    if op == "ArgMax":
        return O.ArgMax()
    if op == "DepthToSpace":
        return O.DepthToSpace(node.attr["block_size"].i)
    if op == "SpaceToDepth":
        return O.SpaceToDepth(node.attr["block_size"].i)
    if op == "GatherV2":
        return O.GatherV2()
    if op == "OneHot":
        return O.OneHot(node.attr["axis"].i if "axis" in node.attr else -1)
    if op in ("BatchMatMul", "BatchMatMulV2"):
        return O.BatchMatMul(node.attr["adj_x"].b, node.attr["adj_y"].b)
    if op == "Cumsum":
        return O.Cumsum(node.attr["exclusive"].b, node.attr["reverse"].b)
    if op == "Range":
        return O.RangeOp()
    if op == "ZerosLike":
        return O.ZerosLike()
    if op == "OnesLike":
        return O.OnesLike()
    if op == "Shape":
        return O.Shape()
    if op == "LogSoftmax":
        return O.LogSoftmax()
    if op in ("TopKV2", "TopK"):
        return O.TopKV2()
    if op in ("Switch", "RefSwitch"):
        return O.SwitchOp()
    if op in ("Enter", "RefEnter"):
        return O.EnterOp(node.attr["frame_name"].s.decode()
                         if "frame_name" in node.attr else "",
                         node.attr["is_constant"].b)
    if op in ("Exit", "RefExit"):
        return O.ExitOp()
    if op in ("NextIteration", "RefNextIteration"):
        return O.NextIterationOp()
    if op == "LoopCond":
        return O.LoopCondOp()
    raise NotImplementedError(
        f"TF op {op!r} (node {node.name!r}) has no bigdl_tpu lowering yet")


def _np_dtype(tf_enum: int):
    """TF DataType enum → numpy dtype (the slots imported graphs cast to)."""
    table = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
             5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
             14: "bfloat16", 19: np.float16, 22: np.uint32, 23: np.uint64}
    if tf_enum not in table:
        raise NotImplementedError(f"Cast to TF dtype enum {tf_enum}")
    dt = table[tf_enum]
    if dt == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return dt


class TensorflowLoader:
    """Reference-shaped facade: ``TensorflowLoader.load(path, inputs,
    outputs)`` (reference ``Module.loadTF``)."""

    load = staticmethod(load_tf)


class TFSession:
    """Limited training-graph support (reference ``utils/tf/Session.scala``).

    The reference could drive simple TF TRAINING graphs; the analog here is
    that an imported (frozen) graph stays fully trainable — every Const
    feeding a weight slot was promoted to a trainable ``ParameterOp`` — so a
    Session wraps the imported ``Graph`` with the Optimizer plumbing for
    fine-tuning:

        sess = TFSession(graph_def, inputs=["x"], outputs=["logits"])
        model = sess.model                      # trainable bigdl_tpu Graph
        sess.train(samples, criterion, batch_size=32, end_trigger=...)
    """

    def __init__(self, graph_def_or_path, inputs, outputs) -> None:
        self.model = load_tf(graph_def_or_path, inputs, outputs)

    def train(self, samples, criterion, batch_size: int = 32,
              end_trigger=None, optim_method=None):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        opt = Optimizer(
            model=self.model, dataset=DataSet.array(list(samples)),
            criterion=criterion, batch_size=batch_size,
            end_trigger=end_trigger or Trigger.max_epoch(1))
        opt.set_optim_method(optim_method or SGD(learning_rate=0.01))
        return opt.optimize()
