"""TensorflowLoader — import a frozen TensorFlow GraphDef as a Graph.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/utils/tf/
TensorflowLoader.scala`` + ``.../utils/tf/loaders/*`` — parses a frozen
GraphDef, maps each node onto ``nn/ops`` modules, and wires a BigDL
``Graph``. Same architecture here: ``load_tf(path, inputs, outputs)`` walks
the GraphDef, lowers each node to a ``bigdl_tpu.nn.ops`` module (NHWC, no
layout shuffling — XLA assigns layouts), promotes Variables/Consts feeding
weight slots to trainable params, and returns a ``Graph`` whose forward
matches TF's execution of the same graph.

The protobuf parsing itself uses the installed ``tensorflow`` package (the
reference equally linked TF's protos); no TF runtime executes the model.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.nn import ops as O
from bigdl_tpu.nn.graph import Graph, Input, ModuleNode


def _attr(node, name, default=None):
    if name not in node.attr:
        return default
    return node.attr[name]


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util

    return np.asarray(tensor_util.MakeNdarray(node.attr["value"].tensor))


def _strides(node) -> List[int]:
    return list(node.attr["strides"].list.i)


def _padding(node) -> str:
    return node.attr["padding"].s.decode()


def _ksize(node) -> List[int]:
    return list(node.attr["ksize"].list.i)


# ops whose ONLY job is passthrough
_IDENTITY_OPS = {"Identity", "StopGradient", "CheckNumerics", "PlaceholderWithDefault"}

# table-returning ops: consumers address their results by port ("name:1");
# the loader inserts a SelectTable per referenced port
_MULTI_OUTPUT_OPS = {"Split", "SplitV", "Unpack", "Unstack", "TopKV2", "TopK"}

# weight-slot positions per op: input indices that, when fed by a Const,
# should become trainable ParameterOps rather than frozen ConstOps
_TRAINABLE_SLOTS = {
    "Conv2D": {1},
    "DepthwiseConv2dNative": {1},
    "MatMul": {1},
    "BiasAdd": {1},
    "FusedBatchNorm": {1, 2},
    "FusedBatchNormV3": {1, 2},
}


def load_tf(graph_def_or_path, inputs: Sequence[str], outputs: Sequence[str],
            generated_backward: bool = True) -> Graph:
    """Build a :class:`Graph` from a frozen GraphDef.

    ``inputs``/``outputs``: TF node names (``"x"`` or ``"scope/x:N"``).
    Multi-output ops (Split/SplitV/Unpack/TopK) are addressed by their port
    suffix — consumers and graph outputs get a per-port ``SelectTable``.
    """
    gd = _load_graph_def(graph_def_or_path)
    nodes: Dict[str, object] = {n.name: n for n in gd.node}
    strip = lambda name: name.split(":")[0].lstrip("^")
    input_names = [strip(n) for n in inputs]
    output_names = [strip(n) for n in outputs]

    built: Dict[str, ModuleNode] = {}
    graph_inputs: List[ModuleNode] = []

    def const_feed(name: str, consumer_op: str, slot: int) -> ModuleNode:
        node = nodes[name]
        value = _const_value(node)
        trainable = slot in _TRAINABLE_SLOTS.get(consumer_op, set())
        # a rank-1 const added/subtracted is a bias in disguise (TF lowers
        # `matmul(x, w) + b` to AddV2, not BiasAdd) — keep it trainable
        if consumer_op in ("Add", "AddV2", "Sub") and value.ndim == 1:
            trainable = True
        mod = O.ParameterOp(value) if trainable else O.ConstOp(value)
        mod.set_name(name)
        # constants have no graph predecessors: hang them off a shared
        # zero-input — our Graph requires every node reachable from inputs,
        # so constants attach to the first real input node as a dummy dep
        return mod

    port_nodes: Dict[tuple, ModuleNode] = {}

    def build_port(name: str, port: int) -> ModuleNode:
        base = build(name)
        if nodes[strip(name)].op not in _MULTI_OUTPUT_OPS:
            return base
        key = (strip(name), port)
        if key not in port_nodes:
            from bigdl_tpu.nn import SelectTable

            sel = SelectTable(port + 1)  # 1-based
            sel.set_name(f"{strip(name)}:{port}")
            port_nodes[key] = sel.inputs(base)
        return port_nodes[key]

    def build(name: str) -> ModuleNode:
        name = strip(name)
        if name in built:
            return built[name]
        node = nodes[name]
        op = node.op

        if name in input_names:
            mn = Input()
            graph_inputs.append(mn)
            built[name] = mn
            return mn

        if op in ("Placeholder",):
            raise ValueError(
                f"Placeholder {name!r} is not listed in inputs={input_names}")

        if op in _IDENTITY_OPS:
            src = node.input[0]
            src_port = int(src.split(":")[1]) if ":" in src else 0
            mn = build_port(strip(src), src_port)
            built[name] = mn
            return mn

        if op == "Const":
            raise ValueError(
                f"Const {name!r} used outside a recognized operand slot")

        preds: List[ModuleNode] = []
        const_mods: List[tuple] = []
        for i, inp in enumerate(node.input):
            if inp.startswith("^"):
                continue  # control edge
            iname = strip(inp)
            port = int(inp.split(":")[1]) if ":" in inp else 0
            src = nodes[iname]
            # resolve through identity chains for const-ness detection
            seen = set()
            while src.op in _IDENTITY_OPS and src.input:
                if src.name in seen:
                    break
                seen.add(src.name)
                src = nodes[strip(src.input[0])]
            if src.op == "Const" and iname not in input_names:
                const_mods.append((i, const_feed(src.name, op, i)))
                preds.append(None)  # placeholder, filled below
            else:
                preds.append(build_port(iname, port))

        mod = _lower(node)
        mod.set_name(name)

        # wire constants: each const module becomes a node fed by the first
        # real predecessor (dummy dep to keep the DAG rooted at inputs)
        anchor = next((p for p in preds if p is not None), None)
        for i, cmod in const_mods:
            if anchor is None:
                # op with only-const operands: anchor on the graph input
                anchor = graph_inputs[0] if graph_inputs else build(input_names[0])
            preds[i] = cmod.inputs(anchor)

        mn = mod.inputs(*preds)
        built[name] = mn
        return mn

    # roots first so const anchoring has an input available
    for n in input_names:
        build(n)
    out_nodes = []
    for n in outputs:
        port = int(str(n).split(":")[1]) if ":" in str(n) else 0
        out_nodes.append(build_port(strip(str(n)), port))
    g = Graph(graph_inputs if len(graph_inputs) > 1 else graph_inputs[0],
              out_nodes if len(out_nodes) > 1 else out_nodes[0])
    return g


def _load_graph_def(graph_def_or_path):
    if isinstance(graph_def_or_path, (str, bytes)) and not isinstance(
            graph_def_or_path, bytes):
        from tensorflow.core.framework import graph_pb2

        gd = graph_pb2.GraphDef()
        with open(graph_def_or_path, "rb") as f:
            gd.ParseFromString(f.read())
        return gd
    return graph_def_or_path  # already a GraphDef


def _lower(node):
    """GraphDef node → nn.ops module (the loaders/* table)."""
    op = node.op
    if op == "Conv2D":
        return O.Conv2D(_strides(node), _padding(node))
    if op == "DepthwiseConv2dNative":
        return O.DepthwiseConv2dNative(_strides(node), _padding(node))
    if op == "BiasAdd":
        return O.BiasAdd()
    if op == "MatMul":
        return O.MatMul(node.attr["transpose_a"].b, node.attr["transpose_b"].b)
    if op == "MaxPool":
        return O.MaxPool(_ksize(node), _strides(node), _padding(node))
    if op == "AvgPool":
        return O.AvgPool(_ksize(node), _strides(node), _padding(node))
    if op in ("FusedBatchNorm", "FusedBatchNormV3"):
        eps = node.attr["epsilon"].f or 1e-3
        return O.FusedBatchNorm(eps)
    if op == "Reshape":
        return O.Reshape()
    if op == "Squeeze":
        dims = list(node.attr["squeeze_dims"].list.i)
        return O.Squeeze(dims or None)
    if op == "ExpandDims":
        return O.ExpandDims()
    if op == "ConcatV2":
        return O.ConcatV2()
    if op == "Pad":
        return O.Pad()
    if op == "Mean":
        return O.Mean(node.attr["keep_dims"].b)
    if op in ("Add", "AddV2"):
        return O.Add()
    if op == "Sub":
        return O.Sub()
    if op == "Mul":
        return O.Mul()
    if op == "RealDiv":
        return O.RealDiv()
    if op == "Maximum":
        return O.Maximum()
    if op == "Rsqrt":
        return O.Rsqrt()
    if op == "AddN":
        from bigdl_tpu.nn.shape_ops import CAddTable

        return CAddTable()
    if op == "Neg":
        from bigdl_tpu.nn.layers_extra import Negative

        return Negative()
    if op == "Softplus":
        from bigdl_tpu.nn.activations import SoftPlus

        return SoftPlus()
    if op == "LeakyRelu":
        from bigdl_tpu.nn.activations import LeakyReLU

        alpha = (node.attr["alpha"].f if "alpha" in node.attr
                 else 0.2)  # 0.0 is a valid (plain-ReLU) alpha
        return LeakyReLU(alpha)
    if op == "Exp":
        from bigdl_tpu.nn.misc import Exp

        return Exp()
    if op == "Log":
        from bigdl_tpu.nn.misc import Log

        return Log()
    if op == "Sqrt":
        from bigdl_tpu.nn.misc import Sqrt

        return Sqrt()
    if op == "Square":
        from bigdl_tpu.nn.misc import Square

        return Square()
    if op == "Softmax":
        return O.Softmax()
    if op == "Relu":
        from bigdl_tpu.nn.activations import ReLU

        return ReLU()
    if op == "Relu6":
        from bigdl_tpu.nn.activations import ReLU6

        return ReLU6()
    if op == "Tanh":
        from bigdl_tpu.nn.activations import Tanh

        return Tanh()
    if op == "Sigmoid":
        from bigdl_tpu.nn.activations import Sigmoid

        return Sigmoid()
    if op == "Minimum":
        return O.Minimum()
    if op == "Pow":
        return O.Pow()
    if op == "FloorDiv":
        return O.FloorDiv()
    if op == "FloorMod":
        return O.FloorMod()
    if op == "SquaredDifference":
        return O.SquaredDifference()
    if op == "Greater":
        return O.Greater()
    if op == "GreaterEqual":
        return O.GreaterEqual()
    if op == "Less":
        return O.Less()
    if op == "LessEqual":
        return O.LessEqual()
    if op == "Equal":
        return O.Equal()
    if op == "NotEqual":
        return O.NotEqual()
    if op == "LogicalAnd":
        return O.LogicalAnd()
    if op == "LogicalOr":
        return O.LogicalOr()
    if op == "LogicalNot":
        return O.LogicalNot()
    if op == "Abs":
        return O.Abs()
    if op == "Floor":
        return O.Floor()
    if op == "Ceil":
        return O.Ceil()
    if op == "Round":
        return O.Round()
    if op == "Sign":
        return O.Sign()
    if op == "Elu":
        return O.Elu()
    if op == "Selu":
        return O.Selu()
    if op == "Erf":
        return O.Erf()
    if op == "Reciprocal":
        return O.Reciprocal()
    if op == "Cast":
        return O.Cast(_np_dtype(node.attr["DstT"].type))
    if op == "Transpose":
        return O.Transpose()
    if op == "Tile":
        return O.TileOp()
    if op == "Slice":
        return O.SliceOp()
    if op == "StridedSlice":
        return O.StridedSlice(node.attr["begin_mask"].i,
                              node.attr["end_mask"].i,
                              node.attr["shrink_axis_mask"].i,
                              node.attr["new_axis_mask"].i,
                              node.attr["ellipsis_mask"].i)
    if op in ("Pack", "Stack"):
        return O.PackOp(node.attr["axis"].i)
    if op in ("Unpack", "Unstack"):
        return O.Unpack(node.attr["axis"].i, node.attr["num"].i or None)
    if op == "Split":
        return O.SplitOp(node.attr["num_split"].i)
    if op == "SplitV":
        return O.SplitV()
    if op == "Fill":
        return O.Fill()
    if op in ("Select", "SelectV2"):
        return O.Select()
    if op == "ClipByValue":
        return O.ClipByValue()
    if op == "Sum":
        return O.Sum(node.attr["keep_dims"].b)
    if op == "Max":
        return O.Max(node.attr["keep_dims"].b)
    if op == "Min":
        return O.Min(node.attr["keep_dims"].b)
    if op == "Prod":
        return O.Prod(node.attr["keep_dims"].b)
    if op == "ArgMax":
        return O.ArgMax()
    if op == "DepthToSpace":
        return O.DepthToSpace(node.attr["block_size"].i)
    if op == "SpaceToDepth":
        return O.SpaceToDepth(node.attr["block_size"].i)
    if op == "GatherV2":
        return O.GatherV2()
    if op == "OneHot":
        return O.OneHot(node.attr["axis"].i if "axis" in node.attr else -1)
    if op in ("BatchMatMul", "BatchMatMulV2"):
        return O.BatchMatMul(node.attr["adj_x"].b, node.attr["adj_y"].b)
    if op == "Cumsum":
        return O.Cumsum(node.attr["exclusive"].b, node.attr["reverse"].b)
    if op == "Range":
        return O.RangeOp()
    if op == "ZerosLike":
        return O.ZerosLike()
    if op == "OnesLike":
        return O.OnesLike()
    if op == "Shape":
        return O.Shape()
    if op == "LogSoftmax":
        return O.LogSoftmax()
    if op in ("TopKV2", "TopK"):
        return O.TopKV2()
    raise NotImplementedError(
        f"TF op {op!r} (node {node.name!r}) has no bigdl_tpu lowering yet")


def _np_dtype(tf_enum: int):
    """TF DataType enum → numpy dtype (the slots imported graphs cast to)."""
    table = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
             5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
             14: "bfloat16", 19: np.float16, 22: np.uint32, 23: np.uint64}
    if tf_enum not in table:
        raise NotImplementedError(f"Cast to TF dtype enum {tf_enum}")
    dt = table[tf_enum]
    if dt == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return dt


class TensorflowLoader:
    """Reference-shaped facade: ``TensorflowLoader.load(path, inputs,
    outputs)`` (reference ``Module.loadTF``)."""

    load = staticmethod(load_tf)


class TFSession:
    """Limited training-graph support (reference ``utils/tf/Session.scala``).

    The reference could drive simple TF TRAINING graphs; the analog here is
    that an imported (frozen) graph stays fully trainable — every Const
    feeding a weight slot was promoted to a trainable ``ParameterOp`` — so a
    Session wraps the imported ``Graph`` with the Optimizer plumbing for
    fine-tuning:

        sess = TFSession(graph_def, inputs=["x"], outputs=["logits"])
        model = sess.model                      # trainable bigdl_tpu Graph
        sess.train(samples, criterion, batch_size=32, end_trigger=...)
    """

    def __init__(self, graph_def_or_path, inputs, outputs) -> None:
        self.model = load_tf(graph_def_or_path, inputs, outputs)

    def train(self, samples, criterion, batch_size: int = 32,
              end_trigger=None, optim_method=None):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        opt = Optimizer(
            model=self.model, dataset=DataSet.array(list(samples)),
            criterion=criterion, batch_size=batch_size,
            end_trigger=end_trigger or Trigger.max_epoch(1))
        opt.set_optim_method(optim_method or SGD(learning_rate=0.01))
        return opt.optimize()
