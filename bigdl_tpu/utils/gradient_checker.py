"""GradientChecker — finite-difference vs analytic gradient validation.

Reference (UNVERIFIED, SURVEY.md §0): ``.../nn/GradientChecker.scala`` —
per-layer numerical gradient checks used throughout the reference's layer
specs (SURVEY.md §4 test strategy).

Same contract here, over the pure core: central differences on the loss
``sum(apply(params, x))`` against ``jax.grad``, elementwise relative
comparison. Runs in fp64-ish tolerance territory by doing the finite
differences in fp32 with a configurable epsilon.
"""

from __future__ import annotations

import numpy as np


class GradientChecker:
    def __init__(self, perturbation: float = 1e-3, precision: float = 1e-2) -> None:
        self.perturbation = perturbation
        self.precision = precision

    def check_layer(self, module, input, check_input: bool = True,
                    check_weight: bool = True) -> bool:
        """True when analytic and numerical gradients agree elementwise
        within ``precision`` (relative, with absolute floor)."""
        import jax
        import jax.numpy as jnp

        module._ensure_params()
        x = jnp.asarray(input)
        params = module.params

        def loss_fn(p, xx):
            out, _ = module.apply(p, xx, module.state or {},
                                  training=False, rng=None)
            leaves = jax.tree_util.tree_leaves(out)
            return sum(jnp.sum(o) for o in leaves)

        ok = True
        if check_weight and jax.tree_util.tree_leaves(params):
            analytic = jax.grad(loss_fn, argnums=0)(params, x)
            ok &= self._compare_tree(
                lambda p: float(loss_fn(p, x)), params, analytic)
        if check_input:
            analytic_x = jax.grad(loss_fn, argnums=1)(params, x)
            ok &= self._compare_tree(
                lambda xx: float(loss_fn(params, xx)), x, analytic_x)
        return bool(ok)

    def _compare_tree(self, loss_of, tree, analytic_tree) -> bool:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        an_leaves = jax.tree_util.tree_leaves(analytic_tree)
        eps = self.perturbation
        for li, (leaf, an) in enumerate(zip(leaves, an_leaves)):
            arr = np.asarray(leaf, np.float32)
            an = np.asarray(an, np.float32)
            flat = arr.reshape(-1)
            # sample up to 32 coordinates (reference checks a subset too)
            idxs = np.linspace(0, flat.size - 1,
                               min(32, flat.size)).astype(int)
            for i in np.unique(idxs):
                fp = flat.copy()
                fp[i] += eps
                fm = flat.copy()
                fm[i] -= eps
                lp = loss_of(self._rebuild(leaves, li, fp.reshape(arr.shape),
                                           treedef))
                lm = loss_of(self._rebuild(leaves, li, fm.reshape(arr.shape),
                                           treedef))
                numeric = (lp - lm) / (2 * eps)
                denom = max(abs(numeric), abs(float(an.reshape(-1)[i])), 1.0)
                if abs(numeric - float(an.reshape(-1)[i])) / denom > self.precision:
                    return False
        return True

    @staticmethod
    def _rebuild(leaves, li, new_leaf, treedef):
        import jax

        out = list(leaves)
        out[li] = new_leaf
        return jax.tree_util.tree_unflatten(treedef, out)
