"""DLEstimator / DLClassifier — ML-pipeline integration.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dlframes/DLEstimator.scala``
— ``DLEstimator``/``DLModel``/``DLClassifier``/``DLClassifierModel`` wrapping
the Optimizer in Spark ML's ``Estimator``/``Transformer`` pipeline contract
(``fit(df) -> model``, ``model.transform(df)``).

TPU-native redesign: the pipeline substrate here is the scikit-learn-style
array contract (the Python ecosystem's equivalent of Spark ML): estimators
take ``(X, y)`` arrays, ``fit`` returns a fitted model, models expose
``transform``/``predict``. The reference's fluent knobs (batch size, epochs,
learning rate, optim method, feature/label sizes) are kept name-for-name.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class DLEstimator:
    """Trains ``model`` against ``criterion`` on (X, y) arrays and returns a
    :class:`DLModel`."""

    def __init__(self, model, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int]) -> None:
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.batch_size = 32
        self.max_epoch = 10
        self.learning_rate = 1e-3
        self.optim_method = None
        self._model_cls = DLModel

    # fluent config (reference setter names, snake_case) -------------------

    def set_batch_size(self, n: int) -> "DLEstimator":
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int) -> "DLEstimator":
        self.max_epoch = n
        return self

    def set_learning_rate(self, lr: float) -> "DLEstimator":
        self.learning_rate = lr
        return self

    def set_optim_method(self, method) -> "DLEstimator":
        self.optim_method = method
        return self

    def _label_array(self, y):
        return np.asarray(y)

    def fit(self, X, y) -> "DLModel":
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        X = np.asarray(X, np.float32)
        y = self._label_array(y)
        samples = [
            Sample(x.reshape(self.feature_size),
                   np.asarray(t).reshape(self.label_size)
                   if self.label_size else t)
            for x, t in zip(X, y)
        ]
        opt = Optimizer(model=self.model, dataset=DataSet.array(samples),
                        criterion=self.criterion, batch_size=self.batch_size)
        opt.set_optim_method(
            self.optim_method or SGD(learning_rate=self.learning_rate))
        opt.set_end_when(Trigger.max_epoch(self.max_epoch))
        trained = opt.optimize()
        return self._model_cls(trained, self.feature_size, self.batch_size)


class DLModel:
    """Fitted transformer: ``transform(X)`` = batched forward."""

    def __init__(self, model, feature_size: Sequence[int],
                 batch_size: int = 32) -> None:
        self.model = model
        self.feature_size = tuple(feature_size)
        self.batch_size = batch_size
        self._predictor = None  # built once; reuses the compiled eval step

    def set_feature_size(self, size: Sequence[int]) -> "DLModel":
        self.feature_size = tuple(size)
        return self

    def set_batch_size(self, n: int) -> "DLModel":
        self.batch_size = n
        return self

    def transform(self, X) -> np.ndarray:
        from bigdl_tpu.optim.evaluator import Predictor

        X = np.asarray(X, np.float32)
        X = X.reshape((X.shape[0],) + self.feature_size)
        # one Predictor for the model's lifetime: its jitted eval step
        # compiles once and is reused across transform calls
        if self._predictor is None:
            self._predictor = Predictor(self.model)
        return np.asarray(self._predictor.predict(X, self.batch_size))

    predict = transform


class DLClassifier(DLEstimator):
    """Classification estimator: scalar 1-based labels, argmax transform
    (reference ``DLClassifier``)."""

    def __init__(self, model, criterion, feature_size: Sequence[int]) -> None:
        super().__init__(model, criterion, feature_size, label_size=())
        self._model_cls = DLClassifierModel

    def _label_array(self, y):
        y = np.asarray(y)
        if y.min() < 1:
            raise ValueError(
                "DLClassifier labels are 1-based (reference convention); "
                f"got minimum label {y.min()}"
            )
        return y.astype(np.float32)


class DLClassifierModel(DLModel):
    """Fitted classifier: ``transform`` returns 1-based class predictions."""

    def transform(self, X) -> np.ndarray:
        scores = DLModel.transform(self, X)
        return scores.argmax(-1) + 1

    predict = transform

    def predict_proba(self, X) -> np.ndarray:
        scores = DLModel.transform(self, X)
        # scores may be log-probs (LogSoftMax heads) or raw logits
        e = np.exp(scores - scores.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)
