"""Trigger — composable stop/fire conditions.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/Trigger.scala`` —
``maxEpoch``, ``maxIteration``, ``everyEpoch``, ``severalIteration``,
``minLoss``, ``maxScore``, ``and``/``or``. Evaluated host-side against the
optimizer's state table each iteration, exactly like the reference.
"""

from __future__ import annotations

from typing import Callable


class Trigger:
    """``fn(state) -> bool`` decides firing; ``peek_fn`` must be a
    SIDE-EFFECT-FREE predictor of ``fn``. The optimizer calls ``peek`` on a
    speculative post-step state to decide batch prefetch, so a stateful
    ``fn`` used as its own peek (the default) would consume its latch on a
    state that never becomes real. Factories below supply correct peeks;
    directly-constructed stateful Triggers must pass ``peek_fn``
    explicitly (the optimizer also guards the loop-top ``next()`` so a
    wrong peek degrades to a clean stop, not a crash)."""

    def __init__(self, fn: Callable[[dict], bool],
                 peek_fn: Callable[[dict], bool] = None) -> None:
        self._fn = fn
        self._peek = peek_fn or fn

    def __call__(self, state) -> bool:
        return self._fn(state)

    def peek(self, state) -> bool:
        """Side-effect-free evaluation: would the trigger fire on this
        state? Stateful triggers (every_epoch) must NOT consume their
        one-shot latch here — the optimizer peeks at a speculative
        post-step state to decide whether to prefetch the next batch."""
        return self._peek(state)

    def and_(self, other: "Trigger") -> "Trigger":
        return Trigger(lambda s: self(s) and other(s),
                       lambda s: self.peek(s) and other.peek(s))

    def or_(self, other: "Trigger") -> "Trigger":
        return Trigger(lambda s: self(s) or other(s),
                       lambda s: self.peek(s) or other.peek(s))

    # -- factories ---------------------------------------------------------

    @staticmethod
    def max_epoch(max_e: int) -> "Trigger":
        return Trigger(lambda s: s["epoch"] > max_e)

    @staticmethod
    def max_iteration(max_it: int) -> "Trigger":
        return Trigger(lambda s: s["neval"] > max_it)

    @staticmethod
    def every_epoch() -> "Trigger":
        holder = {"last": None}

        def would_fire(s):
            return s["epoch"] != holder["last"] and s.get("epoch_finished", False)

        def fn(s):
            if would_fire(s):
                holder["last"] = s["epoch"]
                return True
            return False

        return Trigger(fn, would_fire)

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        return Trigger(lambda s: (s["neval"] - 1) % interval == 0 and s["neval"] > 1)

    @staticmethod
    def min_loss(min_l: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss") is not None and s["loss"] < min_l)

    @staticmethod
    def max_score(max_s: float) -> "Trigger":
        return Trigger(lambda s: s.get("score") is not None and s["score"] > max_s)


# module-level factory aliases matching the reference's Trigger.xxx style
max_epoch = Trigger.max_epoch
max_iteration = Trigger.max_iteration
every_epoch = Trigger.every_epoch
several_iteration = Trigger.several_iteration
min_loss = Trigger.min_loss
max_score = Trigger.max_score
