"""Optimizer — abstract trainer + factory + LocalOptimizer.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/Optimizer.scala``
(fluent config + ``object Optimizer.apply`` dispatching Local vs Distri on
dataset type — the north star keeps this API source-unchanged) and
``LocalOptimizer.scala`` (single-node trainer that clones the model across a
thread pool).

TPU-native redesign of LocalOptimizer: the ``subModelNumber`` thread-pool
data parallelism vanishes — one jitted train step uses the whole chip
(SURVEY.md §2.4 "intra-node DP vanishes"). The optimize() driver loop stays
a thin host loop: fetch host batch → device_put → compiled step, with
trigger/validation/checkpoint/summary cadence identical to the reference.
The bounded retry-from-checkpoint wrapper (§5.3) lives here too.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet, DistributedDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.train_step import make_eval_step, make_train_step
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod

logger = logging.getLogger("bigdl_tpu")


class TrainingPreempted(RuntimeError):
    """Raised when training stops at an iteration boundary because a
    preemption signal (SIGTERM) arrived — AFTER a final checkpoint was
    written. Deliberately not retried by the bounded-retry wrapper: the
    process is being evicted; the restarted job resumes with
    ``optimize(resume=True)``."""


def _natural_key(s: str):
    import re

    return [int(p) if p.isdigit() else p for p in re.split(r"(\d+)", str(s))]


def _digit_skeleton(s: str) -> str:
    import re

    return re.sub(r"\d+", "#", str(s))


def _adapt_restored_tree(template, restored, what: str, _path: str = ""):
    """Reconcile a restored checkpoint tree against the live structure.

    A model rebuilt in the same process gets fresh auto-name counters
    (``Linear13`` where the checkpoint says ``Linear1``), and orbax
    restores tuples as lists. Walk both trees together: dict levels whose
    key sets differ are paired in NATURAL-SORT order (numeric runs compare
    as numbers — i.e. construction order for counter-suffixed names, which
    plain sorted() would scramble across digit-count boundaries), with the
    non-digit skeleton of each paired key required to match; sequences
    pair by position; leaf shapes must agree. Anything else is a real
    architecture mismatch and raises."""
    if restored is None:
        return template
    where = f"{what}{_path}"
    if isinstance(template, dict) and isinstance(restored, dict):
        if len(template) != len(restored):
            raise ValueError(
                f"checkpoint {where} has {len(restored)} entries but the "
                f"model expects {len(template)} — different architecture")
        if set(template) == set(restored):
            return {k: _adapt_restored_tree(template[k], restored[k], what,
                                            f"{_path}/{k}")
                    for k in template}
        tk = sorted(template, key=_natural_key)
        rk = sorted(restored, key=_natural_key)
        out = {}
        for a, b in zip(tk, rk):
            if _digit_skeleton(a) != _digit_skeleton(b):
                raise ValueError(
                    f"checkpoint {where} key {b!r} does not correspond to "
                    f"the model's {a!r} — different architecture")
            out[a] = _adapt_restored_tree(template[a], restored[b], what,
                                          f"{_path}/{a}")
        logger.info(
            "resume: %s keys differ from the live model (rebuilt module "
            "auto-names); matched %s in natural order", where, list(rk))
        return out
    if isinstance(template, (list, tuple)) and \
            isinstance(restored, (list, tuple)):
        if len(template) != len(restored):
            raise ValueError(
                f"checkpoint {where} has {len(restored)} entries but the "
                f"model expects {len(template)} — different architecture")
        vals = [_adapt_restored_tree(t, r, what, f"{_path}[{i}]")
                for i, (t, r) in enumerate(zip(template, restored))]
        return type(template)(vals) if isinstance(template, tuple) else vals
    if isinstance(template, dict) or isinstance(restored, dict) or \
            isinstance(template, (list, tuple)) or \
            isinstance(restored, (list, tuple)):
        raise ValueError(
            f"checkpoint {where} container kind does not match the model "
            "— different architecture")
    if tuple(np.shape(template)) != tuple(np.shape(restored)):
        raise ValueError(
            f"checkpoint {where} has shape {np.shape(restored)} but the "
            f"model expects {np.shape(template)} — different architecture")
    return restored


def _ensure_dataset(dataset, batch_size: Optional[int],
                    drop_remainder: bool = True) -> AbstractDataSet:
    if dataset is None:
        raise ValueError(
            "Optimizer requires a dataset (pass dataset=...; a raw Sample "
            "sequence also needs batch_size=...)"
        )
    if not isinstance(dataset, AbstractDataSet):
        # raw list of Samples → local dataset (pyspark-API convenience)
        if batch_size is None:
            raise ValueError("batch_size required when passing raw samples")
        dataset = DataSet.array(list(dataset))
    if batch_size is not None:
        # Reference semantics: Optimizer(model, sampleRDD, criterion,
        # batchSize) batches a Sample dataset itself; a dataset already
        # yielding MiniBatch (Scala-style transformer chain) passes through.
        probe = next(iter(dataset.data(train=False)), None)
        if isinstance(probe, Sample):
            dataset = dataset.transform(
                SampleToMiniBatch(batch_size, drop_remainder=drop_remainder))
    return dataset


class Optimizer:
    """Fluent training config; ``Optimizer(...)`` returns a Local or Distri
    optimizer based on the dataset type (reference factory semantics)."""

    def __new__(cls, model=None, dataset=None, criterion=None,
                batch_size: Optional[int] = None, end_trigger=None, **kw):
        if cls is Optimizer:
            # dispatch on dataset TYPE only; the side-effecting conversion
            # (list(), probe, SampleToMiniBatch) happens once, in __init__
            if isinstance(dataset, DistributedDataSet) or kw.pop("distributed", False):
                from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

                inst = object.__new__(DistriOptimizer)
            else:
                inst = object.__new__(LocalOptimizer)
            return inst
        return object.__new__(cls)

    def __init__(self, model=None, dataset=None, criterion=None,
                 batch_size: Optional[int] = None, end_trigger=None, **kw):
        self.model = model
        self.dataset = _ensure_dataset(dataset, batch_size)
        self.criterion = criterion
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = end_trigger or Trigger.max_epoch(1)
        self._device_preprocess = None
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_backend = "pickle"
        self.overwrite_checkpoint = True
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: List[ValidationMethod] = []
        self.train_summary = None
        self.validation_summary = None
        self.grad_clip: Dict[str, Any] = {}
        self.compute_dtype = None
        self.loss_scale = 1.0
        self._profile: Optional[Dict[str, Any]] = None
        self.metrics = Metrics()
        self.retry_times = int(os.environ.get("BIGDL_FAILURE_RETRY_TIMES", "5"))
        self.retry_interval_s = float(
            os.environ.get("BIGDL_FAILURE_RETRY_INTERVAL", "1")
        )
        self._handle_preemption = False
        self._preempt_flag = False
        self._async_ckptr = None
        self._async_pending_marker = None

    # -- fluent config (reference names, snake_case) -----------------------

    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_checkpoint(self, path: str = None, trigger: Trigger = None,
                       backend: str = "pickle",
                       # pyspark keyword names
                       checkpoint_trigger: Trigger = None,
                       checkpoint_path: str = None) -> "Optimizer":
        """``backend="pickle"`` writes the reference-style model/optimMethod
        snapshot pair; ``backend="orbax"`` writes an orbax PyTree checkpoint
        (tensor-store format, the TPU-ecosystem standard — SURVEY.md §5.4).

        Accepts both reference dialects: Scala ``(path, trigger)``, pyspark
        positional ``(checkpoint_trigger, checkpoint_path)``, and the
        pyspark keyword names ``checkpoint_trigger=``/``checkpoint_path=``
        (same aliasing policy as ``set_validation``'s val_rdd/val_method).

        On a multi-process pod (``jax.process_count() > 1``) every rank
        writes/reads ``<path>/proc_<rank>`` — give all ranks the SAME
        durable path and each keeps its own shard snapshot (see
        ``_ckpt_dir``)."""
        if isinstance(path, Trigger):          # pyspark positional order
            path, trigger = trigger, path
        # keyword overrides AFTER the swap: a positional Trigger mixed with
        # checkpoint_path= (natural pyspark mix) keeps its trigger
        if checkpoint_trigger is not None:
            trigger = checkpoint_trigger
        if checkpoint_path is not None:
            path = checkpoint_path
        if path is None or trigger is None:
            raise ValueError("set_checkpoint needs both a path and a trigger")
        if backend not in ("pickle", "orbax", "orbax_async"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self.checkpoint_backend = backend
        return self

    def set_device_preprocess(self, fn) -> "Optimizer":
        """Jit-traced preprocessing applied to each input batch ON DEVICE
        before the forward pass — pair with a uint8-NHWC host pipeline
        (``NativeImagePipeline(output="u8_nhwc")`` +
        ``DeviceImageNormalizer``) so host→device transfers ship 4× fewer
        bytes and the normalize fuses into the first conv."""
        self._device_preprocess = fn
        return self

    def handle_preemption(self, enabled: bool = True) -> "Optimizer":
        """TPU-native extension (no reference counterpart — Spark rebuilt
        lost executors; a preempted TPU slice just dies): when enabled,
        a SIGTERM during ``optimize()`` finishes the in-flight iteration,
        writes a final checkpoint (``set_checkpoint`` must be configured),
        and raises :class:`TrainingPreempted` — which the bounded retry
        deliberately does NOT swallow. The restarted job continues with
        ``optimize(resume=True)``. On multi-process pods the scheduler
        delivers SIGTERM to every process of the slice, so each writes
        its own shard checkpoint at the same iteration boundary."""
        self._handle_preemption = bool(enabled)
        return self

    def over_write_checkpoint(self) -> "Optimizer":
        self.overwrite_checkpoint = True
        return self

    def set_validation(self, trigger, dataset=None, methods=None,
                       batch_size: Optional[int] = None,
                       # pyspark keyword names
                       val_rdd=None, val_method=None) -> "Optimizer":
        """Scala order ``(trigger, dataset, methods, batch_size)``; the
        pyspark order ``set_validation(batch_size, val_rdd, trigger,
        val_method)`` is also accepted (detected by an int first arg)."""
        if isinstance(trigger, int):            # pyspark positional order
            batch_size, dataset, trigger, methods = (
                trigger, dataset, methods, batch_size)
        if val_rdd is not None:
            dataset = val_rdd
        if val_method is not None:
            methods = val_method
        self.validation_trigger = trigger
        # keep the trailing partial batch: validation must score EVERY
        # record (reference Evaluator semantics); the mesh eval path pads
        # ragged batches to the data axis and trims the outputs
        self.validation_dataset = _ensure_dataset(dataset, batch_size,
                                                  drop_remainder=False)
        self.validation_methods = list(methods)
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_profile(self, trace_dir: str, start_iteration: int = 5,
                    n_iterations: int = 3) -> "Optimizer":
        """Capture a ``jax.profiler`` trace for iterations
        ``[start_iteration, start_iteration + n_iterations)`` — the deep
        option on top of the reference-style Metrics counters (SURVEY.md
        §5.1); view with TensorBoard's profile plugin or Perfetto."""
        self._profile = {"dir": trace_dir, "start": start_iteration,
                         "stop": start_iteration + n_iterations}
        return self

    def set_compute_dtype(self, dtype) -> "Optimizer":
        """Mixed precision: run forward/backward in ``"bf16"``/``"fp16"``
        while master weights, optimizer state and loss stay fp32 (TPU-native
        performance knob; no reference counterpart — MKL was fp32-only).
        fp16 needs :meth:`set_loss_scale` — its ~6e-8 cotangent floor flushes
        small gradients to zero unscaled (bf16 does not)."""
        self.compute_dtype = dtype
        if dtype in ("fp16", "float16") and self.loss_scale == 1.0:
            logger.warning(
                "fp16 compute without loss scaling will underflow small "
                "gradients; call set_loss_scale(e.g. 1024.0)")
        return self

    def set_loss_scale(self, scale: float) -> "Optimizer":
        """Static loss scaling for fp16 compute (loss × scale before the
        backward pass, gradients ÷ scale after)."""
        self.loss_scale = float(scale)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float) -> "Optimizer":
        self.grad_clip["l2_norm"] = clip_norm
        return self

    def set_constant_gradient_clipping(self, min_v: float, max_v: float) -> "Optimizer":
        self.grad_clip["constant"] = (min_v, max_v)
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        self.grad_clip = {}
        return self

    # -- shared driver helpers --------------------------------------------

    def _state0(self) -> Dict[str, Any]:
        return {
            "epoch": int(self.optim_method.state.get("epoch", 1)),
            "neval": int(self.optim_method.state.get("neval", 1)),
            "loss": None,
            "score": None,
            "epoch_finished": False,
        }

    @staticmethod
    def _pod_rank():
        """(process_count, process_index); (1, 0) when jax is unavailable
        (pure-host tooling contexts that never touch a device)."""
        try:
            import jax

            return jax.process_count(), jax.process_index()
        except Exception:
            return 1, 0

    def _ckpt_dir(self) -> Optional[str]:
        """Effective checkpoint directory: on a multi-process pod every
        rank writes its OWN subdirectory (``proc_<rank>``) under the
        configured path. Ranks given one shared/durable path (the normal
        preemption-survival setup) must not race on a single orbax target
        — and in blockstore mode ``opt_state`` is a per-rank shard of
        IDENTICAL shape, so a rank restoring another rank's slice would
        corrupt optimizer momentum silently, past any shape check."""
        if not self.checkpoint_path:
            return self.checkpoint_path
        n, rank = self._pod_rank()
        if n > 1:
            return os.path.join(self.checkpoint_path, f"proc_{rank}")
        return self.checkpoint_path

    def _write_latest_marker(self, ckpt_dir: str, neval: int) -> None:
        """Sidecar recording the newest snapshot's iteration — cheap for
        peers on a shared path to read at resume time (atomic rename;
        for async saves it may briefly run ahead of a torn final write,
        which resume already treats as absent)."""
        tmp = os.path.join(ckpt_dir, f".LATEST.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(int(neval)))
        os.replace(tmp, os.path.join(ckpt_dir, "LATEST"))

    def _peer_latest_markers(self, exclude_rank=None):
        """{proc dirname: LATEST iteration} for sibling ranks under the
        shared checkpoint path; unreadable/pre-sidecar entries skipped."""
        out = {}
        try:
            siblings = os.listdir(self.checkpoint_path)
        except OSError:
            return out
        for d in sorted(siblings):
            if not d.startswith("proc_") or d == f"proc_{exclude_rank}":
                continue
            try:
                with open(os.path.join(self.checkpoint_path, d,
                                       "LATEST")) as f:
                    out[d] = int(f.read().strip())
            except (OSError, ValueError):
                continue
        return out

    def _pod_common_neval(self, own_neval: int) -> int:
        """On a pod with a SHARED checkpoint path, the iteration every
        rank must resume from: the minimum of all ranks' LATEST sidecars.
        Ranks checkpoint independently, so a kill can leave them holding
        snapshots at different iterations — resuming from mismatched
        iterations would silently offset the data streams and trip the
        end trigger at different steps."""
        if self._pod_rank()[0] <= 1:
            return own_neval
        markers = self._peer_latest_markers()
        if not markers:              # path not shared — nothing visible
            return own_neval
        return min([own_neval] + list(markers.values()))

    def _checkpoint(self, state, params, model_state, opt_state) -> None:
        from bigdl_tpu.utils.file_io import File

        ckpt_dir = self._ckpt_dir()
        if not ckpt_dir:
            return
        tag = "" if self.overwrite_checkpoint else f".{state['neval']}"
        os.makedirs(ckpt_dir, exist_ok=True)
        if self.checkpoint_backend in ("orbax", "orbax_async"):
            import jax
            import orbax.checkpoint as ocp

            target = os.path.abspath(
                os.path.join(ckpt_dir, f"orbax{tag or '.0'}"))
            blob = {
                "params": jax.tree_util.tree_map(np.asarray, params),
                "model_state": jax.tree_util.tree_map(np.asarray, model_state),
                "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
                "epoch": np.int64(state["epoch"]),
                "neval": np.int64(state["neval"]),
                "seen": np.int64(state.get("seen", 0)),
            }
            if self.checkpoint_backend == "orbax_async":
                # TPU-ecosystem async save: the write happens on a
                # background thread while training continues; the only
                # sync points are back-to-back saves and loop exit
                if self._async_ckptr is None:
                    self._async_ckptr = ocp.AsyncCheckpointer(
                        ocp.PyTreeCheckpointHandler())
                self._async_ckptr.wait_until_finished()
                # previous async save is now durable — only NOW may its
                # sidecar go out (a marker ahead of a torn in-flight
                # save would make peers trust an iteration this rank
                # cannot actually restore)
                self._flush_async_marker()
                self._async_ckptr.save(target, blob, force=True)
                self._async_pending_marker = (ckpt_dir, state["neval"])
                return
            ocp.PyTreeCheckpointer().save(target, blob, force=True)
            self._write_latest_marker(ckpt_dir, state["neval"])
            return
        File.save(
            # same blob shape as Module.save, so Module.load() can open a
            # checkpoint snapshot directly (reference resume semantics)
            {"params": params, "state": model_state, "module": self.model},
            os.path.join(ckpt_dir, f"model{tag}"),
            over_write=True,
        )
        File.save(
            {
                "method": self.optim_method,
                "opt_state": opt_state,
                "epoch": state["epoch"],
                "neval": state["neval"],
                "seen": state.get("seen", 0),
            },
            os.path.join(ckpt_dir, f"optimMethod{tag}"),
            over_write=True,
        )
        self._write_latest_marker(ckpt_dir, state["neval"])

    def _flush_async_marker(self) -> None:
        """Write the sidecar for the last CONFIRMED async save. Call only
        after ``wait_until_finished`` — see ``_checkpoint``."""
        if self._async_pending_marker is not None:
            self._write_latest_marker(*self._async_pending_marker)
            self._async_pending_marker = None

    def _pod_rollback(self, own_neval: int, exists_fn, load_fn):
        """Reconcile this rank's newest restorable snapshot against the
        pod-wide common iteration: returns ``load_fn(common)`` when a
        rollback is needed, ``None`` when the own snapshot stands, and
        raises LOUDLY when ranks are skewed but the common snapshot is
        not retained — resuming skewed iterations would silently offset
        the per-rank data streams and end triggers."""
        common = self._pod_common_neval(own_neval)
        if common == own_neval:
            return None
        if self.overwrite_checkpoint or not exists_fn(common):
            raise RuntimeError(
                f"pod resume: this rank's newest checkpoint is at "
                f"iteration {own_neval} but the pod-wide common "
                f"iteration is {common}, and no snapshot for it is "
                "retained (overwrite mode keeps one). Use "
                "over-write=False checkpoints on pods, or align the "
                "per-rank checkpoints manually.")
        try:
            result = load_fn(common)
        except Exception as e:
            raise RuntimeError(
                f"pod resume: the pod-common snapshot at iteration "
                f"{common} exists but is not restorable ({e!r}) — align "
                "the per-rank checkpoints manually") from e
        logger.warning(
            "pod resume: rolled back to the pod-common snapshot at "
            "iteration %d", common)
        return result

    def _assert_pod_peers_not_ahead(self):
        """Guard for the nothing-restorable case: a rank that would start
        FRESH must not do so silently while pod peers resume from their
        snapshots (that is the same silent iteration skew `_pod_rollback`
        exists to stop, through the other door)."""
        n, rank = self._pod_rank()
        if n <= 1 or not self.checkpoint_path:
            return
        peers = self._peer_latest_markers(exclude_rank=rank)
        if peers:
            raise RuntimeError(
                f"pod resume: this rank (proc_{rank}) has no restorable "
                f"checkpoint but pod peers do ({peers}) — starting fresh "
                "would silently skew the pod. Restore this rank's "
                "snapshot or clear every rank's checkpoints.")

    def _latest_checkpoint(self):
        from bigdl_tpu.utils.file_io import File

        ckpt_dir = self._ckpt_dir()
        if not ckpt_dir or not os.path.isdir(ckpt_dir):
            self._assert_pod_peers_not_ahead()
            return None
        if self.checkpoint_backend in ("orbax", "orbax_async"):
            import orbax.checkpoint as ocp

            if self._async_ckptr is not None:
                self._async_ckptr.wait_until_finished()
                self._flush_async_marker()

            def _iteration_of(f):
                # valid snapshots are "orbax.<iter>"; anything else (orbax
                # temp dirs from a crash mid-save) must not break resume
                try:
                    return float(f[len("orbax."):] or 0)
                except ValueError:
                    return None

            snaps = sorted(
                (f for f in os.listdir(ckpt_dir)
                 if f.startswith("orbax") and _iteration_of(f) is not None),
                key=_iteration_of,
            )
            if not snaps:
                self._assert_pod_peers_not_ahead()
                return None
            blob = None
            for snap in reversed(snaps):   # newest first; skip torn ones
                try:
                    blob = ocp.PyTreeCheckpointer().restore(os.path.abspath(
                        os.path.join(ckpt_dir, snap)))
                    break
                except Exception:
                    logger.warning(
                        "resume: snapshot %s is torn — trying older", snap)
            if blob is None:
                self._assert_pod_peers_not_ahead()
                return None

            def _load(c):
                return ocp.PyTreeCheckpointer().restore(os.path.abspath(
                    os.path.join(ckpt_dir, f"orbax.{c}")))

            rb = self._pod_rollback(
                int(blob["neval"]),
                lambda c: os.path.isdir(
                    os.path.join(ckpt_dir, f"orbax.{c}")),
                _load)
            if rb is not None:
                blob = rb
            return (
                {"params": blob["params"], "model_state": blob["model_state"]},
                {"opt_state": blob["opt_state"], "epoch": int(blob["epoch"]),
                 "neval": int(blob["neval"]),
                 "seen": int(blob.get("seen", 0))},
            )
        def _snap_iter(f):
            # numeric ordering: "model.12" must outrank "model.9" (and the
            # overwrite-mode bare "model" sorts first)
            try:
                return float(f[len("model."):] or 0)
            except ValueError:
                return -1.0

        models = sorted(
            (f for f in os.listdir(ckpt_dir)
             if f.startswith("model")),
            key=_snap_iter,
        )
        if not models:
            self._assert_pod_peers_not_ahead()
            return None
        m = o = None
        for f in reversed(models):         # newest first; skip torn ones
            tag = f[len("model"):]
            try:
                m = File.load(os.path.join(ckpt_dir, f"model{tag}"))
                o = File.load(os.path.join(ckpt_dir, f"optimMethod{tag}"))
                break
            except Exception:
                logger.warning(
                    "resume: snapshot model%s is torn — trying older", tag)
                m = o = None
        if o is None:
            self._assert_pod_peers_not_ahead()
            return None

        def _load(c):
            return (File.load(os.path.join(ckpt_dir, f"model.{c}")),
                    File.load(os.path.join(ckpt_dir, f"optimMethod.{c}")))

        rb = self._pod_rollback(
            int(o["neval"]),
            lambda c: os.path.exists(os.path.join(ckpt_dir, f"model.{c}")),
            _load)
        if rb is not None:
            m, o = rb
        return m, o

    def _eval_forward(self, params, model_state, inp):
        import jax

        if not hasattr(self, "_eval_step"):
            self._eval_step = jax.jit(make_eval_step(
                self.model, self._device_preprocess))
        return self._eval_step(params, model_state, inp)

    def _run_validation(self, params, model_state, state) -> Optional[float]:
        if not (self.validation_dataset and self.validation_methods):
            return None
        totals = [None] * len(self.validation_methods)
        for batch in self.validation_dataset.data(train=False):
            inp = batch.get_input() if isinstance(batch, MiniBatch) else batch
            tgt = batch.get_target() if isinstance(batch, MiniBatch) else None
            out = self._eval_forward(params, model_state, inp)
            for i, m in enumerate(self.validation_methods):
                r = m.apply(out, tgt)
                totals[i] = r if totals[i] is None else totals[i] + r
        import jax as _jax

        multi = _jax.process_count() > 1
        score = None
        for m, r in zip(self.validation_methods, totals):
            if r is None:
                if not multi:
                    continue
                # the merge below is a COLLECTIVE: a process whose shard
                # yielded no batches must still participate or the pod
                # deadlocks — contribute a zero accumulator
                r = m.empty_result()
            # pod runs: every process scored its own validation shard;
            # merge to the GLOBAL result (reference driver-side reduce)
            r = r.merge_across_processes()
            val, n_scored = r.result()
            if multi and n_scored == 0:
                continue  # no process had data for this method
            logger.info("validation [%s] epoch %d iter %d: %s",
                        m.name, state["epoch"], state["neval"], r)
            if self.validation_summary is not None:
                self.validation_summary.add_scalar(m.name, val, state["neval"])
            if score is None:
                score = val
        # feed plateau-style schedules
        sched = getattr(self.optim_method, "learning_rate_schedule", None)
        if sched is not None and hasattr(sched, "record_score") and score is not None:
            sched.record_score(score)
        return score

    def optimize(self, resume: bool = False):
        """``resume=True`` restarts from the latest checkpoint under
        ``set_checkpoint``'s path before the first attempt — the pod
        restart-after-kill entry point (within-process failures always
        retry from checkpoint regardless)."""
        if self._handle_preemption and not self.checkpoint_path:
            # configuration error — validate BEFORE the retry loop so it
            # isn't pointlessly retried
            raise ValueError(
                "handle_preemption() needs set_checkpoint(...) configured "
                "— an eviction with nowhere to write the final snapshot "
                "would silently lose all progress")
        last_err = None
        try:
            for attempt in range(self.retry_times):
                try:
                    return self._optimize_once(resume=resume or attempt > 0)
                except (KeyboardInterrupt, SystemExit, TrainingPreempted):
                    raise  # eviction is not a transient failure — no retry
                except Exception as e:  # bounded retry from checkpoint (§5.3)
                    last_err = e
                    logger.exception(
                        "training attempt %d failed; retrying from "
                        "checkpoint", attempt)
                    time.sleep(self.retry_interval_s)
            raise last_err
        finally:
            if self._async_ckptr is not None:
                # release the background save executor (a long-lived
                # process may construct many Optimizers)
                self._async_ckptr.wait_until_finished()
                self._flush_async_marker()
                self._async_ckptr.close()
                self._async_ckptr = None
            self._teardown()

    def _teardown(self) -> None:
        """Subclass hook run when optimize() finishes or fails — drain any
        background machinery (a daemon thread mid-RPC at interpreter
        shutdown aborts the process)."""

    # -- subclass hooks ----------------------------------------------------

    def _prepare(self):
        """Returns (step, place_batch, params, opt_state, model_state).

        ``step(params, opt_state, model_state, rng, inp, tgt)`` is compiled;
        ``place_batch(batch) -> (inp, tgt)`` stages a host MiniBatch onto
        device(s) with the right sharding.
        """
        raise NotImplementedError

    def _writeback(self, params, opt_state, model_state) -> None:
        """Store final (host-layout) params back into the module facade."""
        import jax

        self.model.params = jax.tree_util.tree_map(np.asarray, params)
        self.model.state = jax.tree_util.tree_map(np.asarray, model_state)
        self._final_opt_state = opt_state

    def _ckpt_params_to_host(self, params):
        return params

    def _host_params_to_device(self, params):
        return params

    def _ckpt_opt_state_to_host(self, opt_state):
        return opt_state

    def _opt_state_to_device(self, opt_state):
        return opt_state

    def _optimize_once(self, resume: bool = False):
        import jax

        self.model.training()
        self.model._ensure_params()
        prev_sigterm = None
        if self._handle_preemption:
            import signal

            self._preempt_flag = False

            def _on_sigterm(signum, frame):
                logger.warning(
                    "SIGTERM received: finishing the current iteration, "
                    "checkpointing, then stopping (TrainingPreempted)")
                self._preempt_flag = True

            try:  # signal handlers only install from the main thread
                prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                logger.warning(
                    "handle_preemption: not on the main thread, SIGTERM "
                    "hook not installed")
        try:
            return self._optimize_loop(resume)
        finally:
            if prev_sigterm is not None:
                import signal

                signal.signal(signal.SIGTERM, prev_sigterm)
            if self._async_ckptr is not None:
                self._async_ckptr.wait_until_finished()
                self._flush_async_marker()

    def _optimize_loop(self, resume: bool = False):
        import jax

        step, place_batch, params, opt_state, model_state = self._prepare()
        state = self._state0()

        if resume:
            snap = self._latest_checkpoint()
            if snap is not None:
                mblob, oblob = snap
                # a model rebuilt in the same process gets fresh auto-name
                # counters ("Linear2" vs the checkpoint's "Linear1"), so
                # reconcile restored trees against the live structure by
                # position when only the key names differ
                restored_params = _adapt_restored_tree(
                    self.model.params, mblob["params"], "params")
                params = self._host_params_to_device(restored_params)
                model_state = _adapt_restored_tree(
                    model_state, mblob.get("state", mblob.get("model_state")),
                    "model_state")
                opt_state = self._opt_state_to_device(_adapt_restored_tree(
                    self._ckpt_opt_state_to_host(opt_state),
                    oblob["opt_state"], "opt_state"))
                state["epoch"] = oblob["epoch"]
                state["neval"] = oblob["neval"]
                state["seen"] = oblob.get("seen", 0)
                logger.info("resumed from checkpoint at iteration %d", state["neval"])

        from bigdl_tpu.utils.random_gen import RNG

        base_key = RNG.next_key()

        data_iter = self.dataset.data(train=True)
        epoch_size = self.dataset.size()
        seen_this_epoch = 0
        if resume and state["neval"] > 1:
            # replay the deterministic stream up to the checkpointed
            # position so the continued trajectory consumes exactly the
            # batches an uninterrupted run would (epochs reshuffle by
            # epoch index, so full epochs must be replayed, not skipped)
            target = (state["epoch"] - 1) * epoch_size + state.get("seen", 0)
            skipped = 0
            while skipped < target:
                try:
                    skipped += next(data_iter).size()
                except StopIteration:
                    raise ValueError(
                        f"cannot resume: the data stream ended after "
                        f"{skipped} records but the checkpoint was taken "
                        f"{target} records in — the dataset is smaller (or "
                        f"differently sized) than the one that wrote the "
                        f"checkpoint") from None
            seen_this_epoch = state.get("seen", 0)
        next_ready = None            # (inp, tgt, bsz) placed ahead of time
        epoch_start = time.time()

        while not self.end_when(state):
            if self._preempt_flag:
                self._checkpoint(
                    state, self._ckpt_params_to_host(params), model_state,
                    self._ckpt_opt_state_to_host(opt_state),
                )
                if self._async_ckptr is not None:
                    self._async_ckptr.wait_until_finished()
                    self._flush_async_marker()
                raise TrainingPreempted(
                    f"evicted at iteration {state['neval']}; checkpoint "
                    f"written to {self.checkpoint_path or '(no path set)'}")
            state["epoch_finished"] = False
            if self._profile is not None:
                if state["neval"] == self._profile["start"]:
                    jax.profiler.start_trace(self._profile["dir"])
                    self._profile["active"] = True
                elif state["neval"] == self._profile["stop"] and \
                        self._profile.get("active"):
                    jax.profiler.stop_trace()
                    self._profile["active"] = False
            # input pipelining: the NEXT batch is fetched/placed while the
            # dispatched (async) step still runs on the device; float(loss)
            # is the only host sync point
            if next_ready is None:
                try:
                    b = next(data_iter)
                except StopIteration:
                    logger.warning(
                        "data iterator exhausted before end_when fired; "
                        "stopping. (Possible causes: the iterator yields "
                        "fewer batches than dataset.size() implies, or a "
                        "directly-constructed stateful Trigger without a "
                        "side-effect-free peek_fn.)")
                    break
                next_ready = (*place_batch(b), b.size())
            inp, tgt, bsz = next_ready
            t0 = time.time()
            rng = jax.random.fold_in(base_key, state["neval"])
            params, opt_state, model_state, loss = step(
                params, opt_state, model_state, rng, inp, tgt,
            )
            # prefetch overlaps device compute — but only when the loop
            # will actually run again, so finite/shared iterators never
            # lose a batch to a discarded prefetch. The speculative state
            # mirrors the counter updates below; loss-triggered stops
            # can't be predicted pre-sync and may still prefetch once.
            spec = dict(state)
            spec["neval"] += 1
            spec["epoch_finished"] = seen_this_epoch + bsz >= epoch_size
            if spec["epoch_finished"]:
                spec["epoch"] += 1
            if self.end_when.peek(spec):
                next_ready = None
            else:
                try:
                    b = next(data_iter)      # overlaps device compute
                    next_ready = (*place_batch(b), b.size())
                except StopIteration:
                    # finite custom iterators: end_when decides at loop top
                    next_ready = None
            loss_f = float(loss)
            dt = time.time() - t0
            self.metrics.add("computing time", dt)
            self.metrics.add("records/second", bsz / max(dt, 1e-9))
            state["loss"] = loss_f
            state["neval"] += 1
            self.optim_method.state["neval"] = state["neval"]
            seen_this_epoch += bsz
            state["seen"] = seen_this_epoch

            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss_f, state["neval"] - 1)
                self.train_summary.add_scalar(
                    "Throughput", bsz / max(dt, 1e-9), state["neval"] - 1
                )
                sched = getattr(self.optim_method, "learning_rate_schedule", None)
                base_lr = getattr(self.optim_method, "learning_rate", None)
                if sched is not None and base_lr is not None:
                    # jitted optim state's neval counts from 0, host neval
                    # from 1: the lr JUST applied was sched.lr(neval - 2)
                    self.train_summary.add_scalar(
                        "LearningRate",
                        float(sched.lr(base_lr, max(0, state["neval"] - 2))),
                        state["neval"] - 1,
                    )
                if self.train_summary.should_record("Parameters", state):
                    host = self._ckpt_params_to_host(params)
                    for path, leaf in jax.tree_util.tree_flatten_with_path(
                            host)[0]:
                        tag = "Parameters/" + "/".join(
                            getattr(k, "key", str(k)) for k in path)
                        self.train_summary.add_histogram(
                            tag, np.asarray(leaf), state["neval"] - 1)

            if seen_this_epoch >= epoch_size:
                state["epoch_finished"] = True
                logger.info(
                    "epoch %d done: %d records in %.1fs, last loss %.4f",
                    state["epoch"], seen_this_epoch, time.time() - epoch_start, loss_f,
                )
                state["epoch"] += 1
                self.optim_method.state["epoch"] = state["epoch"]
                seen_this_epoch = 0
                state["seen"] = 0
                epoch_start = time.time()

            if self.validation_trigger is not None and self.validation_trigger(state):
                # device-layout params: DistriOptimizer overrides
                # _eval_forward to evaluate SHARDED over the mesh instead of
                # gathering to host and wasting N-1 chips (SURVEY §3.3)
                score = self._run_validation(params, model_state, state)
                if score is not None:
                    state["score"] = score
            if self.checkpoint_trigger is not None and self.checkpoint_trigger(state):
                self._checkpoint(
                    state, self._ckpt_params_to_host(params), model_state,
                    self._ckpt_opt_state_to_host(opt_state),
                )

        if self._profile is not None and self._profile.get("active"):
            jax.profiler.stop_trace()  # loop ended inside the trace window
            self._profile["active"] = False
        self._writeback(params, opt_state, model_state)
        return self.model


class LocalOptimizer(Optimizer):
    """Single-process trainer driving the local chip(s) with one jitted step.

    Reference ``LocalOptimizer.scala``'s thread-pool model clones vanish:
    one compiled step saturates the chip (SURVEY.md §2.4).
    """

    def _prepare(self):
        import jax

        from bigdl_tpu.optim.train_step import resolve_dtype

        import jax.numpy as jnp

        # fresh device buffers: device_put would alias arrays that already
        # live on device (the module facade's own params), and donating an
        # aliased buffer would delete it out from under model.params
        params = jax.tree_util.tree_map(
            lambda a: jnp.array(a), self.model.params)
        model_state = self.model.state
        opt_state = self.optim_method.init_state(params)
        # donate params+opt_state: XLA updates them in place, halving their
        # peak HBM footprint (they are rebound to the step's outputs anyway)
        step = jax.jit(
            make_train_step(self.model, self.criterion, self.optim_method,
                            self.grad_clip, loss_scale=self.loss_scale,
                            compute_dtype=resolve_dtype(self.compute_dtype),
                            device_preprocess=self._device_preprocess),
            donate_argnums=(0, 1),
        )

        def place_batch(batch: MiniBatch):
            return batch.get_input(), batch.get_target()

        return step, place_batch, params, opt_state, model_state
