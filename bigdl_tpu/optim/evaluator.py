"""Evaluator / Predictor — the batched inference plane.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/Evaluator.scala``
(broadcast model, per-partition batched forward, ``ValidationResult.merge``
reduce — call stack SURVEY.md §3.3) and ``Predictor.scala`` /
``LocalPredictor.scala`` (same shape, returns outputs instead of reducing).

TPU-native redesign: "broadcast + mapPartitions" collapses to ONE jitted
forward. Single chip: plain ``jax.jit``. Mesh: the batch is sharded over the
``data`` axis (``NamedSharding``) and XLA runs the same program on every
chip — the reference's executor fan-out with zero explicit comm (metrics
reduce host-side exactly like ``ValidationResult.merge``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch, Sample, stack_samples
from bigdl_tpu.optim.train_step import make_eval_step
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult


def _batches(data, batch_size: int):
    """Normalize list-of-Samples / arrays / DataSets / MiniBatches →
    MiniBatch stream (DataSet handling shared with the Optimizer)."""
    if isinstance(data, MiniBatch):
        yield data
        return
    if hasattr(data, "data") and callable(getattr(data, "data")):  # DataSet
        from bigdl_tpu.optim.optimizer import _ensure_dataset

        # evaluation scores EVERY record — keep the trailing partial batch
        yield from _ensure_dataset(
            data, batch_size, drop_remainder=False).data(train=False)
        return
    items = list(data) if not isinstance(data, (list, tuple)) else data
    if items and isinstance(items[0], MiniBatch):
        yield from items
        return
    if items and isinstance(items[0], Sample):
        for i in range(0, len(items), batch_size):
            yield stack_samples(items[i:i + batch_size])
    else:  # raw feature arrays
        arr = np.asarray(items, np.float32)
        for i in range(0, len(arr), batch_size):
            yield MiniBatch(arr[i:i + batch_size])


def make_sharded_eval_step(model, mesh, device_preprocess=None):
    """Jitted forward with the batch sharded over the mesh's ``data`` axis
    and params/state replicated — the one construction shared by
    :class:`Evaluator` and ``DistriOptimizer``'s in-training validation.

    ``device_preprocess`` (e.g. the u8-NHWC ``DeviceImageNormalizer``) runs
    inside the jit on the raw sharded batch, mirroring the training step —
    a pipeline that trains through ``set_device_preprocess`` must validate
    through the same transform or the model sees unnormalized input."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    return jax.jit(make_eval_step(model, device_preprocess),
                   in_shardings=(rep, rep, batch_sh), out_shardings=batch_sh)


def pad_shard_call(step, n_dev: int, params, model_state, inp):
    """Run a mesh-sharded eval ``step`` on a batch whose row count may not
    divide the ``data`` axis: pad rows (repeating row 0) to a multiple of
    ``n_dev``, call, trim the outputs back. Shared by :class:`Evaluator`
    and ``DistriOptimizer``'s in-training validation path."""
    n = np.asarray(inp).shape[0] if not isinstance(inp, (list, tuple)) \
        else np.asarray(inp[0]).shape[0]
    pad = (-n) % n_dev
    if not pad:
        return step(params, model_state, inp)

    def pad_rows(x):
        x = np.asarray(x)
        return np.concatenate([x, np.repeat(x[:1], pad, axis=0)])

    inp = ([pad_rows(v) for v in inp]
           if isinstance(inp, (list, tuple)) else pad_rows(inp))
    out = step(params, model_state, inp)
    return ([o[:n] for o in out]
            if isinstance(out, (list, tuple)) else out[:n])


class Evaluator:
    """Distributed/batched evaluation of a model against ValidationMethods
    (reference ``Evaluator(model).test(dataset, methods, batchSize)``)."""

    def __init__(self, model, mesh=None, device_preprocess=None) -> None:
        """``device_preprocess`` mirrors ``Optimizer.set_device_preprocess``:
        a model trained on normalized input through that hook must be
        scored through the same transform, or raw (e.g. uint8-NHWC) batches
        reach the model unnormalized."""
        self.model = model
        self.mesh = mesh
        self.device_preprocess = device_preprocess
        self._step = None

    def _forward(self, params, model_state, inp):
        import jax

        if self._step is None:
            if self.mesh is not None:
                self._step = make_sharded_eval_step(
                    self.model, self.mesh, self.device_preprocess)
            else:
                self._step = jax.jit(
                    make_eval_step(self.model, self.device_preprocess))
        if self.mesh is not None:
            # a ragged final batch can't shard N ways — pad to the mesh size
            n_dev = int(np.prod(list(self.mesh.shape.values())))
            return pad_shard_call(self._step, n_dev, params, model_state, inp)
        return self._step(params, model_state, inp)

    def test(self, dataset, methods: Sequence[ValidationMethod],
             batch_size: int = 32) -> List[ValidationResult]:
        self.model.evaluate()
        self.model._ensure_params()
        params, model_state = self.model.params, self.model.state
        totals: List[Optional[ValidationResult]] = [None] * len(methods)
        for batch in _batches(dataset, batch_size):
            out = self._forward(params, model_state, batch.get_input())
            tgt = batch.get_target()
            for i, m in enumerate(methods):
                r = m.apply(out, tgt)
                totals[i] = r if totals[i] is None else totals[i] + r
        return [t for t in totals if t is not None]


class Predictor:
    """Batched prediction (reference ``Predictor.predict/predictClass``)."""

    def __init__(self, model, mesh=None, device_preprocess=None) -> None:
        self._ev = Evaluator(model, mesh=mesh,
                             device_preprocess=device_preprocess)
        self.model = model

    @staticmethod
    def _restore_batch(a: np.ndarray, n: int) -> np.ndarray:
        """Models whose Reshape heads auto-detect the batch dim drop the
        leading axis on a batch-1 tail — restore it so batches
        concatenate."""
        return a[None] if (a.ndim == 0 or a.shape[0] != n) else a

    def predict(self, data, batch_size: int = 32):
        self.model.evaluate()
        self.model._ensure_params()
        params, model_state = self.model.params, self.model.state
        outs = []
        for b in _batches(data, batch_size):
            n = b.size()
            o = self._ev._forward(params, model_state, b.get_input())
            if isinstance(o, (list, tuple)):  # multi-output model
                o = [self._restore_batch(np.asarray(x), n) for x in o]
            else:
                o = self._restore_batch(np.asarray(o), n)
            outs.append(o)
        if outs and isinstance(outs[0], (list, tuple)):
            return [
                np.concatenate([np.asarray(o[i]) for o in outs], axis=0)
                for i in range(len(outs[0]))
            ]
        return np.concatenate([np.asarray(o) for o in outs], axis=0)

    def predict_class(self, data, batch_size: int = 32) -> np.ndarray:
        """1-based class predictions (Torch convention)."""
        return self.predict(data, batch_size).argmax(axis=-1) + 1


LocalPredictor = Predictor  # single-process alias (reference LocalPredictor)
Validator = Evaluator  # reference alias: Validator drives ValidationMethods
