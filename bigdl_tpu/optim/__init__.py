from bigdl_tpu.optim.optim_method import (
    LarsSGD,
    Adadelta, Adagrad, Adam, Adamax, Default, Exponential, Ftrl,
    LearningRateSchedule, MultiStep, OptimMethod, Plateau, Poly, RMSprop,
    SequentialSchedule, SGD, Step, Warmup,
)
from bigdl_tpu.optim.optimizer import (LocalOptimizer, Optimizer,
                                        TrainingPreempted)
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.optim.evaluator import Evaluator, LocalPredictor, Predictor, Validator
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import (
    AccuracyResult, Loss, LossResult, MAE, Top1Accuracy, TreeNNAccuracy, Top5Accuracy,
    ValidationMethod, ValidationResult,
)
from bigdl_tpu.optim.lbfgs import LBFGS, strong_wolfe
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.regularizer import L1L2Regularizer, L1Regularizer, L2Regularizer

__all__ = [
    "Adadelta", "Adagrad", "Adam", "Adamax", "Default", "Exponential", "Ftrl",
    "LearningRateSchedule", "MultiStep", "OptimMethod", "Plateau", "Poly",
    "RMSprop", "SequentialSchedule", "SGD", "Step", "Warmup",
    "LocalOptimizer", "Optimizer", "DistriOptimizer", "Trigger",
    "TrainingPreempted",
    "Evaluator", "LocalPredictor", "Predictor", "Validator",
    "AccuracyResult", "Loss", "LossResult", "MAE", "Top1Accuracy",
    "Top5Accuracy", "TreeNNAccuracy", "ValidationMethod", "ValidationResult",
    "LBFGS", "strong_wolfe", "LarsSGD",
    "Metrics", "L1L2Regularizer", "L1Regularizer", "L2Regularizer",
]
