"""OptimMethods — SGD (with embedded LR schedules), Adam, Adagrad, Adadelta,
Adamax, RMSprop, Ftrl.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/SGD.scala``,
``Adam.scala``, ``OptimMethod.scala`` — Torch-convention updates with state
held in a ``Table`` (``state("epoch")``, ``state("neval")``,
``state("evalCounter")``); SGD embeds the LR schedule family (``Default``,
``Step``, ``MultiStep``, ``Exponential``, ``Poly``, ``Plateau``, ``Warmup``,
``SequentialSchedule``).

TPU-native redesign: each method is a **pure jittable update**
``update(grads, state, params) -> (new_params, new_state)`` over arbitrary
pytrees — slot buffers and the step counter live in the state pytree, and LR
schedules are traced functions of the (int32) step counter, so the whole
optimizer step compiles into the SPMD train step (and shards per-partition in
the ZeRO-style partitioned-parameter mode, mirroring the reference's
owner-updates-its-slice design). The reference's ``optimize(feval, x)``
facade is kept for API parity and per-method unit tests. ``Plateau`` is
host-driven (it depends on validation scores), matching the reference's
driver-side trigger cadence.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils.table import Table


# ---------------------------------------------------------------------------
# learning-rate schedules (SGD.scala inner classes)
# ---------------------------------------------------------------------------


class LearningRateSchedule:
    def lr(self, base_lr: float, step):
        """Traced: ``step`` is an int32 scalar (neval - 1)."""
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + step * learningRateDecay) — reference SGD default."""

    def __init__(self, learning_rate_decay: float = 0.0) -> None:
        self.learning_rate_decay = learning_rate_decay

    def lr(self, base_lr, step):
        return base_lr / (1.0 + step * self.learning_rate_decay)


class Step(LearningRateSchedule):
    def __init__(self, step_size: int, gamma: float) -> None:
        self.step_size = step_size
        self.gamma = gamma

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        return base_lr * self.gamma ** jnp.floor(step / self.step_size)


class MultiStep(LearningRateSchedule):
    def __init__(self, step_sizes: Sequence[int], gamma: float) -> None:
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        exponent = sum(
            (step >= s).astype(jnp.float32) for s in self.step_sizes
        )
        return base_lr * self.gamma ** exponent


class Exponential(LearningRateSchedule):
    def __init__(self, decay_step: int, decay_rate: float,
                 stair_case: bool = False) -> None:
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        e = step / self.decay_step
        if self.stair_case:
            e = jnp.floor(e)
        return base_lr * self.decay_rate ** e


class Poly(LearningRateSchedule):
    """lr * (1 - step/maxIteration)^power — Inception-v1's schedule."""

    def __init__(self, power: float, max_iteration: int) -> None:
        self.power = power
        self.max_iteration = max_iteration

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return base_lr * (1.0 - frac) ** self.power


class Warmup(LearningRateSchedule):
    """Linear ramp by ``delta`` per step for ``iteration_num`` steps
    (reference ``SGD.Warmup``; ResNet ImageNet warmup+step recipe chains it
    inside a SequentialSchedule)."""

    def __init__(self, delta: float, iteration_num: Optional[int] = None) -> None:
        self.delta = delta
        self.iteration_num = iteration_num

    def lr(self, base_lr, step):
        return base_lr + step * self.delta


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for ``iterations`` steps
    (reference ``SGD.SequentialSchedule``)."""

    def __init__(self, iteration_per_schedule: Optional[int] = None) -> None:
        self.schedules: List[Tuple[LearningRateSchedule, int]] = []

    def add(self, schedule: LearningRateSchedule, iterations: int) -> "SequentialSchedule":
        self.schedules.append((schedule, iterations))
        return self

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        out = None
        offset = 0
        for i, (sched, iters) in enumerate(self.schedules):
            local = sched.lr(base_lr, step - offset)
            if out is None:
                out = local
            else:
                out = jnp.where(step >= offset, local, out)
            offset += iters
        return out


class Plateau(LearningRateSchedule):
    """Reduce-on-plateau; host-driven via ``record_score`` between steps
    (reference ``SGD.Plateau``)."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0) -> None:
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._scale = 1.0
        self._best: Optional[float] = None
        self._wait = 0
        self._cooldown_left = 0

    def record_score(self, score: float) -> None:
        improved = (
            self._best is None
            or (self.mode == "min" and score < self._best - self.epsilon)
            or (self.mode == "max" and score > self._best + self.epsilon)
        )
        if improved:
            self._best = score
            self._wait = 0
        elif self._cooldown_left > 0:
            self._cooldown_left -= 1
        else:
            self._wait += 1
            if self._wait >= self.patience:
                self._scale *= self.factor
                self._wait = 0
                self._cooldown_left = self.cooldown

    def lr(self, base_lr, step):
        import jax.numpy as jnp

        return jnp.maximum(base_lr * self._scale, self.min_lr)


# ---------------------------------------------------------------------------
# optimization methods
# ---------------------------------------------------------------------------


def _tree_map(f, *trees):
    import jax

    return jax.tree_util.tree_map(f, *trees)


class OptimMethod:
    """Base: pure ``init_state``/``update`` + reference ``optimize`` facade."""

    def __init__(self) -> None:
        self.state = Table(epoch=1, neval=1)  # reference-style host state

    # pure core ---------------------------------------------------------

    def init_state(self, params) -> Dict[str, Any]:
        import jax.numpy as jnp

        return {"neval": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params):
        raise NotImplementedError

    # facade ------------------------------------------------------------

    def optimize(self, feval: Callable, x):
        """Reference contract: ``feval(x) -> (loss, grad)``; updates x in
        place of the return. Host-level; used by tests and LBFGS-style use."""
        loss, grad = feval(x)
        if not hasattr(self, "_facade_state") or self._facade_state is None:
            self._facade_state = self.init_state(x)
        new_x, self._facade_state = self.update(grad, self._facade_state, x)
        self.state["neval"] = self.state.get("neval", 1) + 1
        return new_x, [float(np.asarray(loss))]

    def get_learning_rate(self) -> float:
        return getattr(self, "learning_rate", 0.0)

    def clear_history(self) -> "OptimMethod":
        self._facade_state = None
        self.state = Table(epoch=1, neval=1)
        return self

    # persistence (reference OptimMethod.save/load)
    def save(self, path: str, over_write: bool = False) -> "OptimMethod":
        from bigdl_tpu.utils.file_io import File

        File.save(self, path, over_write=over_write)
        return self

    @staticmethod
    def load(path: str) -> "OptimMethod":
        from bigdl_tpu.utils.file_io import File

        return File.load(path)


class SGD(OptimMethod):
    def __init__(
        self,
        learning_rate: float = 1e-3,
        learning_rate_decay: float = 0.0,
        weight_decay: float = 0.0,
        momentum: float = 0.0,
        dampening: Optional[float] = None,
        nesterov: bool = False,
        learning_rate_schedule: Optional[LearningRateSchedule] = None,
    ) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = dampening if dampening is not None else momentum and 0.0
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or (self.dampening or 0.0) != 0):
            raise ValueError("nesterov requires momentum > 0 and dampening = 0")
        self.learning_rate_schedule = learning_rate_schedule or Default(
            learning_rate_decay
        )

    def init_state(self, params):
        import jax.numpy as jnp

        s: Dict[str, Any] = {"neval": jnp.zeros((), jnp.int32)}
        if self.momentum > 0:
            s["velocity"] = _tree_map(jnp.zeros_like, params)
        return s

    def update(self, grads, state, params):
        clr = self.learning_rate_schedule.lr(self.learning_rate, state["neval"])
        if self.weight_decay > 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p, grads, params)
        new_state = dict(state)
        if self.momentum > 0:
            damp = self.dampening or 0.0
            vel = _tree_map(
                lambda v, g: self.momentum * v + (1.0 - damp) * g,
                state["velocity"], grads,
            )
            new_state["velocity"] = vel
            if self.nesterov:
                grads = _tree_map(lambda g, v: g + self.momentum * v, grads, vel)
            else:
                grads = vel
        new_params = _tree_map(lambda p, g: p - clr * g, params, grads)
        new_state["neval"] = state["neval"] + 1
        return new_params, new_state


def stochastic_round(x, dtype, key):
    """Unbiased fp32 → bf16 cast: add uniform 16-bit noise below the kept
    mantissa, truncate (E[result] = x, unlike round-to-nearest whose bias
    accumulates over thousands of tiny Adam updates when the weights
    themselves are stored bf16). Non-finite values pass through the
    deterministic cast — adding noise to inf/nan bit patterns corrupts
    them."""
    import jax
    import jax.numpy as jnp

    if jnp.dtype(dtype) != jnp.bfloat16:
        raise ValueError("stochastic_round targets bfloat16 storage")
    xf = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(xf, jnp.uint32)
    noise = jax.random.bits(key, xf.shape, jnp.uint16).astype(jnp.uint32)
    rounded = jax.lax.bitcast_convert_type(
        (bits + noise) & jnp.uint32(0xFFFF0000), jnp.float32)
    return jnp.where(jnp.isfinite(xf), rounded,
                     xf).astype(jnp.bfloat16)


class Adam(OptimMethod):
    """Torch-convention Adam.

    ``state_dtype`` stores the m/v slot buffers in a reduced dtype
    (``"bf16"``) — the update math still runs fp32 (cast-in/cast-out), so
    this is purely an HBM-traffic/footprint lever: 2× less slot traffic
    per step, at bf16's ~3-decimal-digit slot precision (measured on the
    137M-param LM in benchmarks/llm_mfu_bench.py ``--sweep_opt``).

    ``stochastic_rounding=True`` makes the parameter write-back unbiased
    when the PARAMS themselves are stored bf16 ("bf16 masters"): the
    fp32 update result is stochastically rounded into the bf16 leaf
    (plain round-to-nearest silently drops updates smaller than half the
    param's ulp — the classic bf16-master failure). Ignored for fp32
    params. The noise key derives from the step counter, so the update
    stays a pure function of (grads, state, params)."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8,
                 state_dtype: Optional[str] = None,
                 stochastic_rounding: bool = False) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        if state_dtype not in (None, "bf16", "bfloat16"):
            raise ValueError(
                f"state_dtype must be None or 'bf16', got {state_dtype!r}")
        self.state_dtype = state_dtype
        self.stochastic_rounding = bool(stochastic_rounding)

    def _slot_dtype(self, leaf_dtype):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.state_dtype else leaf_dtype

    def init_state(self, params):
        import jax.numpy as jnp

        def zeros(p):
            return jnp.zeros(jnp.shape(p), self._slot_dtype(p.dtype))

        return {
            "neval": jnp.zeros((), jnp.int32),
            "m": _tree_map(zeros, params),
            "v": _tree_map(zeros, params),
        }

    def update(self, grads, state, params):
        import jax
        import jax.numpy as jnp

        t = state["neval"] + 1
        clr = self.learning_rate / (1.0 + state["neval"] * self.learning_rate_decay)
        # slot math in fp32 regardless of storage dtype (bf16 squares
        # underflow at ~1e-20 gradient magnitude; fp32 accumulate is free
        # on the VPU)
        m32 = _tree_map(
            lambda m_, g: self.beta1 * m_.astype(jnp.float32)
            + (1 - self.beta1) * g.astype(jnp.float32),
            state["m"], grads)
        v32 = _tree_map(
            lambda v_, g: self.beta2 * v_.astype(jnp.float32)
            + (1 - self.beta2) * jnp.square(g.astype(jnp.float32)),
            state["v"], grads)
        bc1 = 1.0 - self.beta1 ** t.astype(jnp.float32)
        bc2 = 1.0 - self.beta2 ** t.astype(jnp.float32)

        def step_leaf(p, m_, v_):
            return p.astype(jnp.float32) - clr * (m_ / bc1) / (
                jnp.sqrt(v_ / bc2) + self.epsilon)

        new32 = _tree_map(step_leaf, params, m32, v32)
        if self.stochastic_rounding:
            leaves, treedef = jax.tree_util.tree_flatten(new32)
            p_leaves = jax.tree_util.tree_leaves(params)
            key = jax.random.PRNGKey(0)
            key = jax.random.fold_in(key, t)
            out = []
            for i, (n, p) in enumerate(zip(leaves, p_leaves)):
                if jnp.dtype(p.dtype) == jnp.bfloat16:
                    out.append(stochastic_round(
                        n, jnp.bfloat16, jax.random.fold_in(key, i)))
                else:
                    out.append(n.astype(p.dtype))
            new_params = jax.tree_util.tree_unflatten(treedef, out)
        else:
            new_params = _tree_map(
                lambda n, p: n.astype(p.dtype), new32, params)
        m = _tree_map(lambda n, s: n.astype(s.dtype), m32, state["m"])
        v = _tree_map(lambda n, s: n.astype(s.dtype), v32, state["v"])
        return new_params, {"neval": t, "m": m, "v": v}


class Adagrad(OptimMethod):
    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_state(self, params):
        import jax.numpy as jnp

        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        import jax.numpy as jnp

        if self.weight_decay > 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p, grads, params)
        clr = self.learning_rate / (1.0 + state["neval"] * self.learning_rate_decay)
        accum = _tree_map(lambda a, g: a + g * g, state["accum"], grads)
        new_params = _tree_map(
            lambda p, g, a: p - clr * g / (jnp.sqrt(a) + 1e-10), params, grads, accum
        )
        return new_params, {"neval": state["neval"] + 1, "accum": accum}


class Adadelta(OptimMethod):
    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10) -> None:
        super().__init__()
        self.decay_rate = decay_rate
        self.epsilon = epsilon
        self.learning_rate = 1.0

    def init_state(self, params):
        import jax.numpy as jnp

        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": _tree_map(jnp.zeros_like, params),
            "delta_accum": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        import jax.numpy as jnp

        rho, eps = self.decay_rate, self.epsilon
        accum = _tree_map(lambda a, g: rho * a + (1 - rho) * g * g,
                          state["accum"], grads)
        delta = _tree_map(
            lambda g, a, d: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
            grads, accum, state["delta_accum"],
        )
        delta_accum = _tree_map(
            lambda d_, d: rho * d_ + (1 - rho) * d * d, state["delta_accum"], delta
        )
        new_params = _tree_map(lambda p, d: p - d, params, delta)
        return new_params, {
            "neval": state["neval"] + 1,
            "accum": accum,
            "delta_accum": delta_accum,
        }


class Adamax(OptimMethod):
    # reference default epsilon is 1e-38 (double); that is subnormal in
    # float32 and flushes to zero on XLA:CPU/TPU -> 0/0. Use 1e-8.
    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def init_state(self, params):
        import jax.numpy as jnp

        return {
            "neval": jnp.zeros((), jnp.int32),
            "m": _tree_map(jnp.zeros_like, params),
            "u": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        import jax.numpy as jnp

        t = state["neval"] + 1
        m = _tree_map(lambda m_, g: self.beta1 * m_ + (1 - self.beta1) * g,
                      state["m"], grads)
        u = _tree_map(
            lambda u_, g: jnp.maximum(self.beta2 * u_, jnp.abs(g) + self.epsilon),
            state["u"], grads,
        )
        bc = 1.0 - self.beta1 ** t.astype(jnp.float32)
        new_params = _tree_map(
            lambda p, m_, u_: p - (self.learning_rate / bc) * m_ / u_, params, m, u
        )
        return new_params, {"neval": t, "m": m, "u": u}


class RMSprop(OptimMethod):
    def __init__(self, learning_rate: float = 1e-2,
                 learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.decay_rate = decay_rate
        self.epsilon = epsilon

    def init_state(self, params):
        import jax.numpy as jnp

        return {
            "neval": jnp.zeros((), jnp.int32),
            "sq": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        import jax.numpy as jnp

        clr = self.learning_rate / (1.0 + state["neval"] * self.learning_rate_decay)
        sq = _tree_map(
            lambda s, g: self.decay_rate * s + (1 - self.decay_rate) * g * g,
            state["sq"], grads,
        )
        new_params = _tree_map(
            lambda p, g, s: p - clr * g / (jnp.sqrt(s) + self.epsilon),
            params, grads, sq,
        )
        return new_params, {"neval": state["neval"] + 1, "sq": sq}


class Ftrl(OptimMethod):
    """Follow-the-regularized-leader (reference ``optim/Ftrl.scala``)."""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_power: float = -0.5,
                 initial_accumulator_value: float = 0.1,
                 l1_regularization_strength: float = 0.0,
                 l2_regularization_strength: float = 0.0) -> None:
        super().__init__()
        self.learning_rate = learning_rate
        self.lr_power = learning_rate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength

    def init_state(self, params):
        import jax.numpy as jnp

        return {
            "neval": jnp.zeros((), jnp.int32),
            "accum": _tree_map(lambda p: jnp.full_like(p, self.init_accum), params),
            "linear": _tree_map(jnp.zeros_like, params),
        }

    def update(self, grads, state, params):
        import jax.numpy as jnp

        lr, p_ = self.learning_rate, self.lr_power

        def upd(w, g, a, l):
            new_a = a + g * g
            sigma = (new_a ** -p_ - a ** -p_) / lr
            new_l = l + g - sigma * w
            quad = new_a ** -p_ / lr + 2.0 * self.l2
            l1_part = jnp.clip(new_l, -self.l1, self.l1)
            new_w = (l1_part - new_l) / quad
            return new_w, new_a, new_l

        flat = _tree_map(upd, params, grads, state["accum"], state["linear"])
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(
            flat, is_leaf=lambda x: isinstance(x, tuple)
        )
        new_params = treedef.unflatten([x[0] for x in leaves])
        accum = treedef.unflatten([x[1] for x in leaves])
        linear = treedef.unflatten([x[2] for x in leaves])
        return new_params, {
            "neval": state["neval"] + 1,
            "accum": accum,
            "linear": linear,
        }


class LarsSGD(SGD):
    """Layer-wise Adaptive Rate Scaling SGD.

    Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/LarsSGD.scala``
    (set up inside ``DistriOptimizer.optimize()`` for large-batch training,
    SURVEY.md §3.1). Per-parameter-tensor trust ratio
    ``trust · ||w|| / (||g|| + wd·||w||)`` rescales the learning rate, then
    momentum applies as in SGD — the standard LARS formulation.
    """

    def __init__(self, learning_rate: float = 1e-2, momentum: float = 0.9,
                 weight_decay: float = 0.0, trust: float = 0.001,
                 epsilon: float = 1e-9,
                 learning_rate_schedule: Optional[LearningRateSchedule] = None) -> None:
        super().__init__(learning_rate=learning_rate, momentum=momentum,
                         weight_decay=weight_decay,
                         learning_rate_schedule=learning_rate_schedule)
        self.trust = trust
        self.epsilon = epsilon

    def update(self, grads, state, params):
        import jax.numpy as jnp

        clr = self.learning_rate_schedule.lr(self.learning_rate, state["neval"])

        def local_lr(p, g):
            # trust ratio from the RAW gradient norm (decay enters the
            # denominator exactly once, per the LARS formulation)
            wn = jnp.linalg.norm(jnp.ravel(p))
            gn = jnp.linalg.norm(jnp.ravel(g))
            ratio = self.trust * wn / (gn + self.weight_decay * wn
                                       + self.epsilon)
            # scalar-ish leaves (norm 0) fall back to the global rate
            return jnp.where(wn > 0, ratio, 1.0)

        ratios = _tree_map(lambda p, g: local_lr(p, g), params, grads)
        if self.weight_decay > 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p,
                              grads, params)
        scaled = _tree_map(lambda r, g: r * g, ratios, grads)
        new_state = dict(state)
        if self.momentum > 0:
            vel = _tree_map(
                lambda v, g: self.momentum * v + clr * g,
                state["velocity"], scaled,
            )
            new_state["velocity"] = vel
            step = vel
        else:
            step = _tree_map(lambda g: clr * g, scaled)
        new_params = _tree_map(lambda p, s: p - s, params, step)
        new_state["neval"] = state["neval"] + 1
        return new_params, new_state
