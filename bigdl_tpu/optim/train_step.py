"""Train-step builder — the compiled hot loop.

This replaces the reference's entire per-iteration machinery
(``DistriOptimizer.train()``'s thread-pool forward/backward, gradient
summing, and ``AllReduceParameter`` exchange — SURVEY.md §3.1): the forward,
loss, backward, gradient aggregation, clipping, regularization and optimizer
update trace into ONE jitted XLA program. On a mesh, gradient aggregation is
an XLA collective over ICI inserted by sharding propagation (or explicit
psum_scatter/all_gather in the partitioned path in ``bigdl_tpu.parallel``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


def cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype`` (mixed-precision
    helper; integer leaves like token ids pass through untouched)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        tree,
    )


def restore_dtypes(tree, ref):
    """Cast ``tree``'s leaves back to the dtypes of the matching ``ref``
    leaves (keeps BatchNorm running stats at their fp32 storage dtype)."""
    import jax

    return jax.tree_util.tree_map(
        lambda a, r: a.astype(r.dtype)
        if hasattr(a, "dtype") and hasattr(r, "dtype") else a,
        tree, ref,
    )


def resolve_dtype(dtype):
    """Accept "bf16"/"fp16"/"fp32" strings or jnp dtypes (user-facing API)."""
    import jax.numpy as jnp

    if dtype is None or not isinstance(dtype, str):
        return dtype
    table = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
             "fp16": jnp.float16, "float16": jnp.float16,
             "fp32": None, "float32": None}
    if dtype not in table:
        raise ValueError(
            f"unknown compute dtype {dtype!r}; expected one of {sorted(table)}")
    return table[dtype]


def clip_by_global_norm(grads, max_norm: float):
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def clip_by_value(grads, min_v: float, max_v: float):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda g: jnp.clip(g, min_v, max_v), grads)


def child_for_key(module, key):
    """Resolve a params-dict key to the owning sub-module, or None when the
    key is one of ``module``'s own parameter leaves. Container/Graph keys
    are "{i}:{name}" — the ONE place that convention is parsed (the
    regularizer and frozen-mask walks both route through here)."""
    subs = module.sub_modules()
    if not subs:
        return None
    try:
        idx = int(str(key).split(":", 1)[0])
    except (ValueError, IndexError):
        return None
    if idx < len(subs):
        return subs[idx]
    return None


def apply_module_regularizers(model, params, grads):
    """Apply per-layer regularizers (reference: inside accGradParameters).

    Walks the module tree alongside the params pytree; a module with
    ``w_regularizer``/``u_regularizer``/``b_regularizer`` contributes extra
    gradient terms for its weight/recurrent-weight/bias leaves (the key sets
    come from the module's ``_reg_w_keys``/``_reg_u_keys``/``_reg_b_keys``,
    so recurrent cells' ``w_ih``/``w_hh``/``b_*`` participate too).
    """
    def walk(module, p, g):
        if not isinstance(p, dict):
            return g
        out = dict(g)
        for reg_attr, keys_attr, default_keys in (
            ("w_regularizer", "_reg_w_keys", ("weight",)),
            ("u_regularizer", "_reg_u_keys", ("w_hh",)),
            ("b_regularizer", "_reg_b_keys", ("bias", "b_ih", "b_hh")),
        ):
            reg = getattr(module, reg_attr, None)
            if reg is None:
                continue
            for key in getattr(module, keys_attr, default_keys):
                if key in p:
                    out[key] = reg.grad_update(p[key], g[key])
        for key in p:
            child = child_for_key(module, key)
            if child is not None and isinstance(p[key], dict):
                out[key] = walk(child, p[key], g[key])
        return out

    return walk(model, params, grads)


def frozen_mask_tree(model, params):
    """Pytree of python bools mirroring ``params``: True where the owning
    module is frozen (``Module.freeze`` — reference transfer-learning
    freeze). Tri-state inheritance: a module's explicit flag overrides the
    inherited one, so ``model.freeze(); model.unfreeze("head")`` trains
    the head. Returns None when nothing is frozen, so the hot path pays
    zero cost."""
    import jax

    found = [False]

    def mark(module, p, inherited):
        flag = module.frozen_flag()
        frozen = inherited if flag is None else flag
        if not isinstance(p, dict):
            found[0] = found[0] or frozen
            return frozen
        out = {}
        for key, v in p.items():
            child = child_for_key(module, key)
            if child is not None and isinstance(v, dict):
                out[key] = mark(child, v, frozen)
            else:
                if frozen and jax.tree_util.tree_leaves(v):
                    found[0] = True
                out[key] = jax.tree_util.tree_map(lambda _: frozen, v)
        return out

    mask = mark(model, params, False)
    return mask if found[0] else None


def apply_frozen(mask, new_params, old_params):
    """Restore frozen leaves after the optimizer update — zeroed grads
    alone would still let in-optimizer weight decay move them."""
    import jax

    return jax.tree_util.tree_map(
        lambda f, newp, oldp: oldp if f else newp,
        mask, new_params, old_params)


def zero_frozen_grads(mask, grads):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda f, g: jnp.zeros_like(g) if f else g, mask, grads)


def regularizer_loss(model, params):
    """Sum of per-layer regularizer penalties as one scalar loss term —
    gradient-equivalent to ``apply_module_regularizers`` but usable when full
    gradients are never materialized (partitioned distributed path)."""
    total = 0.0

    def walk(module, p):
        nonlocal total
        if not isinstance(p, dict):
            return
        for reg_attr, keys_attr, default_keys in (
            ("w_regularizer", "_reg_w_keys", ("weight",)),
            ("u_regularizer", "_reg_u_keys", ("w_hh",)),
            ("b_regularizer", "_reg_b_keys", ("bias", "b_ih", "b_hh")),
        ):
            reg = getattr(module, reg_attr, None)
            if reg is None:
                continue
            for key in getattr(module, keys_attr, default_keys):
                if key in p:
                    total = total + reg.loss_term(p[key])
        subs = module.sub_modules()
        if subs:
            for key in p:
                try:
                    idx = int(key.split(":", 1)[0])
                except (ValueError, IndexError):
                    continue
                if idx < len(subs):
                    walk(subs[idx], p[key])

    walk(model, params)
    return total


def make_train_step(
    model,
    criterion,
    optim_method,
    grad_clip: Optional[dict] = None,
    grad_transform: Optional[Callable] = None,
    loss_scale: float = 1.0,
    compute_dtype: Optional[Any] = None,
    device_preprocess: Optional[Callable] = None,
):
    """Returns pure ``step(params, opt_state, model_state, rng, inp, tgt)``
    → ``(params, opt_state, model_state, loss)``. Caller jits (possibly with
    shardings).

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) enables mixed precision: master
    weights, optimizer state, criterion and update stay fp32; the forward/
    backward run with params+activations cast to the compute dtype, which is
    where the MXU's 2× bf16 rate and the HBM-bandwidth halving come from.
    Buffer (BatchNorm running stats) dtypes are preserved across steps.

    ``loss_scale`` multiplies the loss before the backward pass and divides
    the gradients after — needed with fp16 compute, whose ~6e-8 cotangent
    floor otherwise flushes small gradients to zero (bf16 shares fp32's
    exponent range and usually needs none).

    ``device_preprocess`` runs INSIDE the jit on the raw input batch
    before anything else — the uint8-NHWC transfer path
    (``DeviceImageNormalizer``): the host ships quarter-size uint8
    batches and the normalize/transpose fuses into the first conv's
    prologue on device.
    """

    def step(params, opt_state, model_state, rng, inputs, targets):
        import jax
        import jax.numpy as jnp

        def loss_fn(p):
            x = inputs
            if device_preprocess is not None:
                x = device_preprocess(x)
            if compute_dtype is not None:
                p = cast_floats(p, compute_dtype)
                x = cast_floats(x, compute_dtype)
            out, new_ms = model.apply(p, x, model_state, training=True, rng=rng)
            if compute_dtype is not None:
                out = cast_floats(out, jnp.float32)  # fp32 stable softmax
                new_ms = restore_dtypes(new_ms, model_state)
            loss = criterion.apply(out, targets)
            return loss * loss_scale, new_ms

        (loss, new_ms), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if loss_scale != 1.0:
            loss = loss / loss_scale
            grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
        grads = apply_module_regularizers(model, params, grads)
        frozen = frozen_mask_tree(model, params)
        if frozen is not None:
            grads = zero_frozen_grads(frozen, grads)
        if grad_transform is not None:
            grads = grad_transform(grads)
        if grad_clip:
            if grad_clip.get("l2_norm") is not None:
                grads = clip_by_global_norm(grads, grad_clip["l2_norm"])
            if grad_clip.get("constant") is not None:
                lo, hi = grad_clip["constant"]
                grads = clip_by_value(grads, lo, hi)
        new_params, new_opt = optim_method.update(grads, opt_state, params)
        if frozen is not None:
            new_params = apply_frozen(frozen, new_params, params)
        return new_params, new_opt, new_ms, loss

    return step


def make_eval_step(model, device_preprocess: Optional[Callable] = None):
    def step(params, model_state, inputs):
        if device_preprocess is not None:
            inputs = device_preprocess(inputs)
        out, _ = model.apply(params, inputs, model_state, training=False, rng=None)
        return out

    return step
