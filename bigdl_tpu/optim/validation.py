"""ValidationMethods + results.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/ValidationMethod.scala``
— ``Top1Accuracy``, ``Top5Accuracy``, ``Loss``, ``MAE``;
``ValidationResult``/``AccuracyResult`` with ``+`` merge (the executor→driver
reduction). Labels are 1-based like the criterions.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ValidationResult:
    #: numeric accumulator fields, in constructor order — the generic
    #: cross-process merge (pod validation) sums them over all processes
    _fields = ()

    def result(self):
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError

    def merge_across_processes(self) -> "ValidationResult":
        """Sum this result's accumulators over every JAX process (the
        executor→driver reduce of reference ``ValidationResult.merge``,
        as one small all-gather). No-op in single-process runs."""
        import jax

        if jax.process_count() == 1 or not self._fields:
            return self
        from jax.experimental import multihost_utils

        states = multihost_utils.process_allgather(
            np.asarray([getattr(self, f) for f in self._fields], np.float64))
        return type(self)(*np.sum(states, axis=0).tolist())


class AccuracyResult(ValidationResult):
    _fields = ("correct", "count")
    def __init__(self, correct: int, count: int) -> None:
        self.correct = int(correct)
        self.count = int(count)

    def result(self):
        acc = self.correct / self.count if self.count else 0.0
        return acc, self.count

    def __add__(self, other: "AccuracyResult") -> "AccuracyResult":
        return AccuracyResult(self.correct + other.correct, self.count + other.count)

    def __repr__(self) -> str:
        acc, n = self.result()
        return f"Accuracy(correct={self.correct}, count={n}, accuracy={acc:.4f})"


class LossResult(ValidationResult):
    _fields = ("loss", "count")

    def __init__(self, loss: float, count: int) -> None:
        self.loss = float(loss)
        self.count = int(count)

    def result(self):
        mean = self.loss / self.count if self.count else 0.0
        return mean, self.count

    def __add__(self, other: "LossResult") -> "LossResult":
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self) -> str:
        mean, n = self.result()
        return f"Loss(mean={mean:.4f}, count={n})"


class ValidationMethod:
    name = "ValidationMethod"
    #: result type with a (0, 0) zero accumulator — pod validation needs an
    #: empty result from processes whose shard produced no batches, so the
    #: cross-process merge collective runs on EVERY process (no deadlock)
    _result_cls = None

    def empty_result(self) -> ValidationResult:
        if self._result_cls is None:
            raise NotImplementedError(
                f"{type(self).__name__} needs _result_cls for pod merges")
        return self._result_cls(0, 0)

    def apply(self, output, target) -> ValidationResult:
        raise NotImplementedError

    __call__ = apply

    def __repr__(self) -> str:
        return self.name


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"
    _result_cls = AccuracyResult

    def apply(self, output, target) -> AccuracyResult:
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64) - 1
        if out.ndim == 1:
            out = out[None]
        pred = out.argmax(axis=-1)
        return AccuracyResult(int((pred == t).sum()), len(t))


class Top5Accuracy(ValidationMethod):
    name = "Top5Accuracy"
    _result_cls = AccuracyResult

    def apply(self, output, target) -> AccuracyResult:
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64) - 1
        if out.ndim == 1:
            out = out[None]
        top5 = np.argsort(-out, axis=-1)[:, :5]
        correct = int(sum(t[i] in top5[i] for i in range(len(t))))
        return AccuracyResult(correct, len(t))


class TreeNNAccuracy(ValidationMethod):
    """Per-node (or root-only) accuracy over tree outputs (reference
    ``TreeNNAccuracy`` used by treeLSTMSentiment).

    ``output``: (B, N, C) per-node class scores in children-before-parent
    node order; ``target``: (B, N) 1-based labels, 0 = padding. Root =
    the LAST labeled node of each tree."""

    _result_cls = AccuracyResult

    def __init__(self, all_nodes: bool = False) -> None:
        self.all_nodes = all_nodes
        self.name = f"TreeNNAccuracy(all={all_nodes})"

    def apply(self, output, target) -> AccuracyResult:
        out = np.asarray(output)
        t = np.asarray(target).astype(np.int64)
        if out.ndim == 2:
            out, t = out[None], np.atleast_2d(t)
        # tolerate BigDL-style trailing singleton label dims: (B, N, 1)
        while t.ndim > out.ndim - 1 and t.shape[-1] == 1:
            t = t[..., 0]
        if t.shape != out.shape[:-1]:
            raise ValueError(
                f"TreeNNAccuracy: target shape {t.shape} does not match "
                f"output node grid {out.shape[:-1]}")
        pred = out.argmax(axis=-1) + 1          # 1-based
        valid = t > 0
        if self.all_nodes:
            correct = int(((pred == t) & valid).sum())
            return AccuracyResult(correct, int(valid.sum()))
        correct = total = 0
        for b in range(t.shape[0]):
            idx = np.nonzero(valid[b])[0]
            if len(idx) == 0:
                continue
            root = idx[-1]
            total += 1
            correct += int(pred[b, root] == t[b, root])
        return AccuracyResult(correct, total)


class Loss(ValidationMethod):
    _result_cls = LossResult
    name = "Loss"

    def __init__(self, criterion=None) -> None:
        if criterion is None:
            from bigdl_tpu.nn.criterion import ClassNLLCriterion

            criterion = ClassNLLCriterion()
        self.criterion = criterion

    def apply(self, output, target) -> LossResult:
        n = np.asarray(output).shape[0]
        return LossResult(self.criterion.forward(output, target) * n, n)


class MAE(ValidationMethod):
    _result_cls = LossResult
    name = "MAE"

    def apply(self, output, target) -> LossResult:
        out = np.asarray(output)
        t = np.asarray(target)
        n = out.shape[0]
        return LossResult(float(np.abs(out - t).mean()) * n, n)
