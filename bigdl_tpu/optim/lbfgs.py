"""LBFGS + line search.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/LBFGS.scala`` +
``LineSearch.scala`` — torch/optim-style L-BFGS: two-loop recursion over an
``nCorrection``-deep curvature history, optional strong-Wolfe cubic line
search (``lswolfe``), tolerances ``tolFun``/``tolX``, eval budget
``maxEval``.

TPU-native shape: the driver loop is host-level (it is inherently
data-dependent — bracketing line search, history pruning), but every vector
operation runs on device over ONE flattened parameter vector, and ``feval``
is expected to be a jitted loss/grad function — so each of the few dozen
evaluations per step is a single compiled launch. This mirrors how the
reference used LBFGS (full-batch, small problems) rather than the
per-minibatch SGD path.

On TPU, run LBFGS under fp32 matmuls (``jax.default_matmul_precision(
"highest")`` or jit the feval with that context): the default bf16 matmul
noise breaks the curvature estimates and strong-Wolfe bracketing that
quasi-Newton methods rely on.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from bigdl_tpu.optim.optim_method import OptimMethod


def _cubic_interpolate(x1, f1, g1, x2, f2, g2):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2); torch recipe."""
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    sq = d1 * d1 - g1 * g2
    if sq < 0:
        return (x1 + x2) / 2.0
    d2 = np.sqrt(sq)
    if x1 <= x2:
        t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
    else:
        t = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
    lo, hi = min(x1, x2), max(x1, x2)
    return float(min(max(t, lo), hi))


def strong_wolfe(feval_dir: Callable, t: float, f0: float, g0: float,
                 c1: float = 1e-4, c2: float = 0.9, max_ls: int = 25):
    """Strong-Wolfe line search along a direction.

    ``feval_dir(t) -> (f, g)`` with g the DIRECTIONAL derivative at step t —
    or ``(f, g, payload)``, in which case the accepted point's payload is
    returned too (LBFGS passes the full gradient vector through here, so the
    search holds at most the bracket's three gradients alive and the caller
    never re-evaluates the accepted point). Returns ``(t, f_t, n_evals)``
    without payloads, ``(t, f_t, n_evals, payload)`` with.
    Reference ``LineSearch.scala — lswolfe``.
    """
    def fe(tt):
        out = feval_dir(tt)
        return out if len(out) == 3 else (out[0], out[1], None)

    has_payload = None
    prev = (0.0, f0, g0, None)          # (t, f, g_dir, payload)
    n_evals = 0
    ft, gt, pt = fe(t)
    has_payload = pt is not None
    cur = (t, ft, gt, pt)
    n_evals += 1

    def ret(point):
        if has_payload:
            return point[0], point[1], n_evals, point[3]
        return point[0], point[1], n_evals

    bracket = None
    for _ in range(max_ls):
        t, f_t, g_t, p_t = cur
        if f_t > f0 + c1 * t * g0 or (n_evals > 1 and f_t >= prev[1]):
            bracket = (prev, cur)
            break
        if abs(g_t) <= -c2 * g0:
            return ret(cur)
        if g_t >= 0:
            bracket = (cur, prev)
            break
        prev = cur
        t = min(10 * t, 1e8)
        ft, gt, pt = fe(t)
        cur = (t, ft, gt, pt)
        n_evals += 1
    if bracket is None:  # ran out of extrapolations
        return ret(cur)
    # zoom phase: lo/hi are full points, so the accepted return always
    # carries its own (f, payload)
    lo, hi = bracket
    for _ in range(max_ls):
        t = _cubic_interpolate(lo[0], lo[1], lo[2], hi[0], hi[1], hi[2])
        span = abs(hi[0] - lo[0])
        if span < 1e-9:
            break
        if min(abs(t - lo[0]), abs(t - hi[0])) < 0.1 * span:
            t = (lo[0] + hi[0]) / 2.0
        ft, gt, pt = fe(t)
        cur = (t, ft, gt, pt)
        n_evals += 1
        if ft > f0 + c1 * t * g0 or ft >= lo[1]:
            hi = cur
        else:
            if abs(gt) <= -c2 * g0:
                return ret(cur)
            if gt * (hi[0] - lo[0]) >= 0:
                hi = lo
            lo = cur
    return ret(lo)


class LBFGS(OptimMethod):
    """Full-batch L-BFGS over the flattened parameter vector."""

    def __init__(self, max_iter: int = 20, max_eval: Optional[int] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: Optional[str] = "strong_wolfe") -> None:
        super().__init__()
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else int(max_iter * 1.25)
        self.tol_fun = tol_fun
        self.tol_x = tol_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def optimize(self, feval: Callable, x):
        """Run up to ``max_iter`` L-BFGS iterations from ``x``.

        ``feval(x) -> (loss, grad)`` over the SAME pytree/array structure as
        ``x``. Returns ``(new_x, [loss history])`` like the reference.
        """
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        flat0, unravel = ravel_pytree(x)

        def fe(v):
            loss, grad = feval(unravel(v))
            gflat, _ = ravel_pytree(grad)
            return float(np.asarray(loss)), gflat

        losses: List[float] = []
        xk = flat0
        f, g = fe(xk)
        losses.append(f)
        n_evals = 1
        s_hist: List = []
        y_hist: List = []
        rho_hist: List[float] = []
        gamma = 1.0

        for it in range(self.max_iter):
            if float(jnp.max(jnp.abs(g))) <= self.tol_fun:
                break  # gradient small enough
            # two-loop recursion — alpha/beta stay traced device scalars so
            # XLA pipelines the whole recursion (no per-entry host syncs)
            q = g
            alphas = []
            for s, y, rho in zip(reversed(s_hist), reversed(y_hist),
                                 reversed(rho_hist)):
                a = rho * jnp.vdot(s, q)
                alphas.append(a)
                q = q - a * y
            d = gamma * q
            for (s, y, rho), a in zip(zip(s_hist, y_hist, rho_hist),
                                      reversed(alphas)):
                b = rho * jnp.vdot(y, d)
                d = d + (a - b) * s
            d = -d
            gtd = float(jnp.vdot(g, d))
            if gtd > -1e-12:  # not a descent direction; reset history
                d = -g
                gtd = -float(jnp.vdot(g, g))
                s_hist, y_hist, rho_hist = [], [], []

            t0 = (self.learning_rate if it > 0 or s_hist
                  else min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(g))), 1e-12))
                  * self.learning_rate)
            accepted = None
            if self.line_search == "strong_wolfe":
                # the full gradient rides through the search as a payload, so
                # at most the bracket's three gradient vectors stay alive and
                # the accepted point's gradient comes back with it
                def fe_dir(t):
                    ft, gt = fe(xk + t * d)
                    return ft, float(jnp.vdot(gt, d)), gt

                t, f_ls, ls_evals, g_ls = strong_wolfe(fe_dir, t0, f, gtd)
                n_evals += ls_evals
                if g_ls is not None:
                    accepted = (f_ls, g_ls)
            else:
                t = t0

            x_new = xk + t * d
            f_old = f
            if accepted is not None:
                f, g_new = accepted
            else:  # no search, or the search degenerated back to t=0
                f, g_new = fe(x_new)
                n_evals += 1
            losses.append(f)

            s = x_new - xk
            y = g_new - g
            ys = float(jnp.vdot(y, s))
            if ys > 1e-10:
                if len(s_hist) >= self.n_correction:
                    s_hist.pop(0)
                    y_hist.pop(0)
                    rho_hist.pop(0)
                s_hist.append(s)
                y_hist.append(y)
                rho_hist.append(1.0 / ys)
                gamma = jnp.asarray(ys) / jnp.vdot(y, y)  # device scalar
            xk, g = x_new, g_new

            if n_evals >= self.max_eval:
                break
            if float(jnp.max(jnp.abs(s))) <= self.tol_x:
                break
            if abs(f - f_old) < self.tol_fun:
                break

        self.state["neval"] = self.state.get("neval", 1) + 1
        return unravel(xk), losses
