"""Metrics — named performance counters.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/Metrics.scala`` —
driver-local + Spark-accumulator-backed counters printed every iteration
(``computing time average``, ``aggregate gradient time``, …). SURVEY.md §5.1.

TPU-native: one process drives the chips, so plain dict counters suffice;
set/add/mean surface kept. Deep profiling is jax.profiler (see
``utils/profiling.py``), layered exactly like the reference layered nothing.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, List[float]] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = [float(value)]

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._values.setdefault(name, []).append(float(value))

    def get(self, name: str) -> Tuple[float, int]:
        """(sum, count) — reference ``Metrics.get``."""
        with self._lock:
            vals = self._values.get(name, [])
            return sum(vals), len(vals)

    def values(self, name: str) -> List[float]:
        """Copy of the raw recorded samples (percentile consumers — e.g.
        serving TTFT — need more than get()'s (sum, count))."""
        with self._lock:
            return list(self._values.get(name, []))

    def mean(self, name: str) -> float:
        total, n = self.get(name)
        return total / n if n else 0.0

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return {k: (sum(v) / len(v) if v else 0.0) for k, v in self._values.items()}

    def clear(self) -> None:
        with self._lock:
            self._values.clear()
