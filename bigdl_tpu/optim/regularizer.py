"""Regularizers.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/Regularizer.scala`` —
``L1Regularizer``/``L2Regularizer``/``L1L2Regularizer`` applied inside
``accGradParameters``.

TPU-native: a pure gradient transform ``grad_update(param, grad) -> grad``
applied inside the jitted train step for layers that carry a regularizer
(and a ``loss_term`` form for totals).
"""

from __future__ import annotations


class Regularizer:
    def grad_update(self, param, grad):
        raise NotImplementedError

    def loss_term(self, param):
        """Equivalent penalty as a loss term (used by the partitioned
        distributed path, where full gradients are never materialized)."""
        raise NotImplementedError


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float = 0.0, l2: float = 0.0) -> None:
        self.l1 = l1
        self.l2 = l2

    def grad_update(self, param, grad):
        import jax.numpy as jnp

        out = grad
        if self.l1 != 0.0:
            out = out + self.l1 * jnp.sign(param)
        if self.l2 != 0.0:
            out = out + self.l2 * param
        return out

    def loss_term(self, param):
        import jax.numpy as jnp

        loss = 0.0
        if self.l1 != 0.0:
            loss = loss + self.l1 * jnp.sum(jnp.abs(param))
        if self.l2 != 0.0:
            loss = loss + 0.5 * self.l2 * jnp.sum(param * param)
        return loss


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float) -> None:
        super().__init__(l1=l1, l2=0.0)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float) -> None:
        super().__init__(l1=0.0, l2=l2)
