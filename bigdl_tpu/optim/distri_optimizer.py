"""DistriOptimizer — the distributed data-parallel trainer.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/optim/DistriOptimizer.scala``
— "the single most important file in the repo": per-executor model caches,
``AllReduceParameter`` gradient partition exchange, straggler gradient-drop,
retry-from-checkpoint, validation/summary/checkpoint triggers (call stack in
SURVEY.md §3.1).

TPU-native redesign: the entire per-iteration Spark job — broadcast, thread
forward/backward, BlockManager reduce-scatter, owner update, allgather —
collapses into ONE jitted shard_map program over a ``jax.sharding.Mesh``:

* batch sharded over the ``data`` mesh axis (one shard per chip — the "one
  executor per TPU chip" of the north star);
* ``parameter_mode="partitioned"`` (default, faithful): params + optimizer
  slots live sharded 1/N per chip; per step: ``all_gather`` weights →
  local fwd/bwd → ``psum_scatter`` grads → owner updates its slice. This is
  ``AllReduceParameter`` verbatim, riding ICI instead of BlockManager.
* ``parameter_mode="allreduce"``: replicated params, ``pmean`` grads,
  identical replicated update — fewer collectives on small models.
* ``compress="bf16"|"fp16"`` mirrors ``FP16CompressedTensor`` on the
  gradient exchange.
* BatchNorm running stats are ``pmean``-ed across shards each step.
* ``parameter_mode="blockstore"``: the reference's BlockManager exchange
  re-created on a host block store ACROSS processes (the DCN boundary),
  with the ``dropPercentage`` straggler gradient-drop
  (``set_drop_module_property`` — see ``parallel/block_store.py``).
  Within a process, gradients still reduce over the local chips with XLA
  collectives; only the cross-process leg rides the store. This is the
  fidelity/straggler mode — the SPMD modes remain the performance path
  (inside one compiled program there is nothing to straggle or drop).

The host driver loop (triggers, checkpoint cadence, bounded retry) is shared
with LocalOptimizer: exactly the thin loop the reference's driver runs.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.optim.train_step import (
    apply_frozen, apply_module_regularizers, cast_floats, clip_by_global_norm,
    clip_by_value, frozen_mask_tree, resolve_dtype, restore_dtypes,
    zero_frozen_grads,
)
from bigdl_tpu.parallel.all_reduce import AllReduceParameter

logger = logging.getLogger("bigdl_tpu")


class DistriOptimizer(Optimizer):
    def __init__(self, model=None, dataset=None, criterion=None,
                 batch_size: Optional[int] = None, end_trigger=None,
                 parameter_mode: str = "partitioned",
                 compress: Optional[str] = None,
                 mesh=None, block_store=None, **kw) -> None:
        # reference semantics: batchSize is GLOBAL. In a multi-process
        # (pod) run each process's dataset shard batches 1/n_proc of it.
        if batch_size is not None:
            import jax

            n_proc = jax.process_count()
            if batch_size % max(n_proc, 1):
                raise ValueError(
                    f"global batch {batch_size} must divide the "
                    f"{n_proc}-process topology")
            batch_size //= max(n_proc, 1)
        super().__init__(model, dataset, criterion, batch_size, end_trigger, **kw)
        if parameter_mode not in ("partitioned", "allreduce", "blockstore"):
            raise ValueError(f"unknown parameter_mode {parameter_mode!r}")
        self.parameter_mode = parameter_mode
        self.compress = compress
        self._mesh = mesh
        self._arp: Optional[AllReduceParameter] = None
        self._block_store = block_store
        self._drop_policy = None
        self._bsp = None

    def set_drop_module_property(self, drop_percentage: float,
                                 max_drop_percentage: Optional[float] = None,
                                 batch_size: int = 100,
                                 warmup_iteration: int = 20) -> "DistriOptimizer":
        """Reference ``setDropModuleProperty`` (SURVEY §5.3): enable
        straggler gradient-drop — after ``warmup_iteration`` iterations
        calibrate arrival-time thresholds over a ``batch_size`` sample
        window, then stop waiting for late gradient contributions once
        ``1 - drop_percentage`` arrived (hard cap ``max_drop_percentage``).

        Only meaningful in ``parameter_mode="blockstore"`` — the SPMD modes
        compile the exchange into one program where partial completion
        cannot exist (that analysis is unchanged); the blockstore mode is
        precisely the reference's BlockManager dataflow where drops are
        well-defined."""
        if self.parameter_mode != "blockstore":
            raise ValueError(
                "gradient drop requires parameter_mode='blockstore' (the "
                "SPMD modes' collectives cannot partially complete; see "
                "parallel/block_store.py)")
        from bigdl_tpu.parallel.block_store import GradientDropPolicy

        self._drop_policy = GradientDropPolicy(
            drop_percentage, max_drop_percentage,
            compute_threshold_batch_size=batch_size,
            warmup_iteration=warmup_iteration)
        return self

    def _teardown(self) -> None:
        # drain the async gradient-put thread: a daemon thread still inside
        # a coordination-KV RPC at interpreter shutdown SIGABRTs (observed
        # as "FATAL: exception not rethrown" in blockstore_bench workers)
        bsp = self._bsp
        if bsp is not None:
            try:
                bsp._join_puts()
            except Exception as e:   # _join_puts wraps stored BaseExceptions
                logger.warning("draining async gradient puts failed: %s", e)

    # -- mesh --------------------------------------------------------------

    def mesh(self):
        if self._mesh is None:
            from bigdl_tpu.utils.engine import Engine

            self._mesh = Engine.mesh(("data",))
        return self._mesh

    # -- spmd step construction -------------------------------------------

    def _grad_hooks(self, grads, params):
        grads = apply_module_regularizers(self.model, params, grads)
        if self.grad_clip.get("l2_norm") is not None:
            grads = clip_by_global_norm(grads, self.grad_clip["l2_norm"])
        if self.grad_clip.get("constant") is not None:
            lo, hi = self.grad_clip["constant"]
            grads = clip_by_value(grads, lo, hi)
        return grads

    def _clip_shard(self, gshard):
        """Gradient clipping on the sharded gradient: the global L2 norm is a
        psum of per-shard square sums (the shards tile the full vector)."""
        import jax.numpy as jnp
        from jax import lax

        if self.grad_clip.get("l2_norm") is not None:
            total = lax.psum(jnp.sum(gshard.astype(jnp.float32) ** 2), "data")
            norm = jnp.sqrt(total)
            gshard = gshard * jnp.minimum(1.0, self.grad_clip["l2_norm"] / (norm + 1e-6))
        if self.grad_clip.get("constant") is not None:
            lo, hi = self.grad_clip["constant"]
            gshard = jnp.clip(gshard, lo, hi)
        return gshard

    def _pmean_state(self, model_state, axis):
        """Average float buffers (BN running stats) across data shards."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        def avg(x):
            if isinstance(x, jnp.ndarray) and jnp.issubdtype(x.dtype, jnp.floating):
                return lax.pmean(x, axis)
            return x

        return jax.tree_util.tree_map(avg, model_state)

    def _build_partitioned_step(self, mesh, params):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bigdl_tpu.utils.compat import shard_map

        n = mesh.devices.size
        arp = AllReduceParameter(params, n, "data", compress=self.compress)
        self._arp = arp
        compute_dtype = resolve_dtype(self.compute_dtype)
        loss_scale = self.loss_scale
        model, criterion, optim = self.model, self.criterion, self.optim_method
        from bigdl_tpu.optim.train_step import regularizer_loss

        # frozen layers (Module.freeze) as a flat mask over the parameter
        # shards, same layout/padding as init_shards
        frozen_tree = frozen_mask_tree(model, params)
        if frozen_tree is None:
            frozen_flat = None
        else:
            mask_leaves = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda p, f: np.full(np.shape(p), bool(f)),
                params, frozen_tree))
            flat = np.concatenate([m.ravel() for m in mask_leaves])
            flat = np.pad(flat, (0, arp.padded_size - flat.size))
            frozen_flat = jnp.asarray(flat.reshape(n, arp.shard_size))

        def spmd(shards, opt_state, model_state, rng, inputs, targets):
            my_shard = shards[0]  # (shard_size,) — this chip's partition
            # per-device slice of the stacked opt state (leading axis 1)
            opt_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
            # decorrelate stochastic layers (dropout) across data shards
            rng = jax.random.fold_in(rng, lax.axis_index("data"))

            # Differentiate w.r.t. THE SHARD: the forward runs the
            # all-gather (getWeights) and the cotangent path runs the
            # compressed reduce-scatter (putGradients +
            # aggregateGradientPartition) — see AllReduceParameter.
            def loss_fn(shard):
                p_full = arp.get_weights(shard)   # fp32 master weights
                p, x = p_full, inputs
                if self._device_preprocess is not None:
                    x = self._device_preprocess(x)
                if compute_dtype is not None:
                    p = cast_floats(p_full, compute_dtype)
                    x = cast_floats(x, compute_dtype)
                out, new_ms = model.apply(p, x, model_state,
                                          training=True, rng=rng)
                if compute_dtype is not None:
                    out = cast_floats(out, jnp.float32)
                    new_ms = restore_dtypes(new_ms, model_state)
                # regularizers act on the fp32 master weights (same policy as
                # the local/allreduce paths' apply_module_regularizers)
                loss = criterion.apply(out, targets) + regularizer_loss(
                    model, p_full)
                return loss * loss_scale, new_ms

            (loss, new_ms), gshard = jax.value_and_grad(loss_fn, has_aux=True)(
                my_shard
            )
            if loss_scale != 1.0:
                loss = loss / loss_scale
                gshard = gshard / loss_scale
            gshard = gshard / n  # sum of per-shard means -> global mean
            if frozen_flat is not None:
                # this device's slice of the flat frozen mask
                fr = frozen_flat[lax.axis_index("data")]
                gshard = jnp.where(fr, 0.0, gshard)
            gshard = self._clip_shard(gshard)
            new_shard, new_opt = optim.update(gshard, opt_local, my_shard)
            if frozen_flat is not None:
                new_shard = jnp.where(fr, my_shard, new_shard)
            new_opt = jax.tree_util.tree_map(lambda x: x[None], new_opt)
            loss = lax.pmean(loss, "data")
            new_ms = self._pmean_state(new_ms, "data")
            return new_shard[None], new_opt, new_ms, loss

        sharded = P("data")
        rep = P()
        step = jax.jit(
            shard_map(
                spmd, mesh=mesh,
                in_specs=(sharded, sharded, rep, rep, sharded, sharded),
                out_specs=(sharded, sharded, rep, rep),
            )
        )

        # initial placement: stacked shards + sharded opt state
        shards_host = arp.init_shards(params)
        dev_shards = jax.device_put(
            shards_host, NamedSharding(mesh, P("data"))
        )
        # vmap broadcasts scalar counters to (n,), slot buffers to (n, shard)
        opt_state = jax.vmap(optim.init_state)(shards_host)
        opt_state = jax.device_put(
            opt_state, NamedSharding(mesh, P("data"))
        )
        return step, dev_shards, opt_state

    def _build_allreduce_step(self, mesh, params):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.utils.compat import device_varying_marker, shard_map

        model, criterion, optim = self.model, self.criterion, self.optim_method
        compute_dtype = resolve_dtype(self.compute_dtype)
        loss_scale = self.loss_scale
        # hoisted once: the mask only depends on static module flags
        frozen = frozen_mask_tree(model, params)

        def spmd(params, opt_state, model_state, rng, inputs, targets):
            rng = jax.random.fold_in(rng, lax.axis_index("data"))
            # mark replicated params device-varying so grads come back LOCAL
            # (jax 0.9 shard_map auto-psums cotangents of unvaried inputs);
            # the pmean below is then the one explicit all-reduce.
            mark_varying = device_varying_marker("data")
            params_v = jax.tree_util.tree_map(mark_varying, params)

            def loss_fn(p):
                x = inputs
                if self._device_preprocess is not None:
                    x = self._device_preprocess(x)
                if compute_dtype is not None:
                    p = cast_floats(p, compute_dtype)
                    x = cast_floats(x, compute_dtype)
                out, new_ms = model.apply(p, x, model_state,
                                          training=True, rng=rng)
                if compute_dtype is not None:
                    out = cast_floats(out, jnp.float32)
                    new_ms = restore_dtypes(new_ms, model_state)
                return criterion.apply(out, targets) * loss_scale, new_ms

            (loss, new_ms), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params_v
            )
            if loss_scale != 1.0:
                loss = loss / loss_scale
                grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
            grads = lax.pmean(grads, "data")
            grads = self._grad_hooks(grads, params)
            if frozen is not None:
                grads = zero_frozen_grads(frozen, grads)
            new_params, new_opt = optim.update(grads, opt_state, params)
            if frozen is not None:
                new_params = apply_frozen(frozen, new_params, params)
            loss = lax.pmean(loss, "data")
            new_ms = self._pmean_state(new_ms, "data")
            return new_params, new_opt, new_ms, loss

        rep, sharded = P(), P("data")
        step = jax.jit(
            shard_map(
                spmd, mesh=mesh,
                in_specs=(rep, rep, rep, rep, sharded, sharded),
                out_specs=(rep, rep, rep, rep),
            )
        )
        opt_state = optim.init_state(params)
        return step, params, opt_state

    # -- blockstore (DCN) mode --------------------------------------------

    @staticmethod
    def _float_leaf_pack(tree):
        """(flat fp32 vector of the float leaves, rebuild(flat) -> tree).
        Non-float leaves (step counters etc.) pass through untouched —
        ``ravel_pytree`` can't be used because averaging ints is wrong."""
        import jax
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        is_f = [np.issubdtype(np.asarray(l).dtype, np.floating)
                for l in leaves]
        flats = [np.asarray(l, np.float32).ravel()
                 for l, f in zip(leaves, is_f) if f]
        flat = (np.concatenate(flats) if flats
                else np.zeros((0,), np.float32))

        def rebuild(vec):
            out, off = [], 0
            for leaf, f in zip(leaves, is_f):
                if f:
                    a = np.asarray(leaf)
                    out.append(vec[off:off + a.size].reshape(a.shape)
                               .astype(a.dtype))
                    off += a.size
                else:
                    out.append(leaf)
            return jax.tree_util.tree_unflatten(treedef, out)

        return flat, rebuild

    def _build_blockstore_step(self, params):
        """The reference's BlockManager parameter plane across processes:
        local chips reduce gradients with XLA collectives (ICI); the
        cross-process leg (DCN) is putGradients / aggregate-with-drop /
        sendWeightPartition / getWeights over a host block store. Owners
        hold optimizer slots for their slice only (the reference kept each
        partition's optimMethod state on its executor's heap)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.flatten_util import ravel_pytree
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from bigdl_tpu.parallel.block_store import (
            BlockStoreParameter, default_block_store,
        )

        n_proc = jax.process_count()
        pid = jax.process_index()
        local_devs = jax.local_devices()
        nl = len(local_devs)
        model, criterion, optim = self.model, self.criterion, self.optim_method
        compute_dtype = resolve_dtype(self.compute_dtype)
        loss_scale = self.loss_scale
        frozen = frozen_mask_tree(model, params)
        from bigdl_tpu.optim.train_step import regularizer_loss

        flat0, unravel = ravel_pytree(params)
        total = int(flat0.shape[0])
        store = self._block_store
        if store is None:
            store = default_block_store()
        if self._bsp is not None:
            # a FAILED attempt's async put thread may still be in flight;
            # drain it BEFORE sweeping, or its stale gradient block can
            # land after the sweep and alias the retried run's
            # same-numbered iteration
            try:
                self._bsp._join_puts()
            except Exception as e:
                logger.warning(
                    "draining previous attempt's gradient puts: %s", e)
        bsp = BlockStoreParameter(
            store, n_proc, pid, total, compress=self.compress,
            drop_policy=self._drop_policy,
            # with gradient-drop on, remote transfers must not sit in
            # front of this process's own weight publish, or a slow
            # transfer stalls every peer at the weight barrier anyway
            # and the drop saves nothing (blockstore_bench.py)
            async_puts=self._drop_policy is not None)
        # a retry-from-checkpoint restarts the iteration counter: reap any
        # blocks a previous attempt left behind so they can't alias the
        # retried run's same-numbered iterations
        bsp.sweep_stale(aux_names=("loss", "gnorm2", "mstate"))
        self._bsp = bsp

        # flat frozen-weight mask in the same padded layout as the shards
        if frozen is None:
            frozen_pad = None
        else:
            mask_leaves = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda p, f: np.full(np.shape(p), bool(f)), params, frozen))
            fr = np.concatenate([m.ravel() for m in mask_leaves])
            frozen_pad = np.pad(fr, (0, bsp.padded_size - fr.size))

        # local gradient program: regularizers are replicated-additive so
        # they commute with the cross-process mean; clipping must act on
        # the AGGREGATED gradient and therefore happens owner-side below
        def local_grad(params, model_state, rng, inputs, targets):
            def loss_fn(p):
                p_master, x = p, inputs
                if self._device_preprocess is not None:
                    x = self._device_preprocess(x)
                if compute_dtype is not None:
                    p = cast_floats(p, compute_dtype)
                    x = cast_floats(x, compute_dtype)
                out, new_ms = model.apply(p, x, model_state,
                                          training=True, rng=rng)
                if compute_dtype is not None:
                    out = cast_floats(out, jnp.float32)
                    new_ms = restore_dtypes(new_ms, model_state)
                # regularizers act on the fp32 master weights AND must see
                # the differentiation variable (a closed-over tree would
                # contribute zero gradient)
                loss = criterion.apply(out, targets) + regularizer_loss(
                    model, p_master)
                return loss * loss_scale, new_ms

            (loss, new_ms), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if loss_scale != 1.0:
                loss = loss / loss_scale
                grads = jax.tree_util.tree_map(
                    lambda g: g / loss_scale, grads)
            return grads, new_ms, loss

        if nl > 1:
            from bigdl_tpu.utils.compat import (
                device_varying_marker, shard_map,
            )

            local_mesh = Mesh(np.asarray(local_devs), ("ldata",))
            mark_varying = device_varying_marker("ldata")

            def spmd(params, model_state, rng, inputs, targets):
                rng = jax.random.fold_in(
                    rng, pid * nl + lax.axis_index("ldata"))
                params = jax.tree_util.tree_map(mark_varying, params)
                grads, new_ms, loss = local_grad(
                    params, model_state, rng, inputs, targets)
                grads = lax.pmean(grads, "ldata")
                loss = lax.pmean(loss, "ldata")
                new_ms = self._pmean_state(new_ms, "ldata")
                return grads, new_ms, loss

            rep, sh = P(), P("ldata")
            grad_step = jax.jit(shard_map(
                spmd, mesh=local_mesh,
                in_specs=(rep, rep, rep, sh, sh),
                out_specs=(rep, rep, rep)))
            batch_sharding = NamedSharding(local_mesh, P("ldata"))
        else:
            def one_dev(params, model_state, rng, inputs, targets):
                rng = jax.random.fold_in(rng, pid)
                return local_grad(params, model_state, rng, inputs, targets)

            grad_step = jax.jit(one_dev)
            batch_sharding = None

        upd = jax.jit(lambda g, o, w: optim.update(g, o, w))
        counter = {"t": 0}
        cache = {"params_ref": None, "wpad": None}
        l2_clip = self.grad_clip.get("l2_norm")
        const_clip = self.grad_clip.get("constant")
        lo_hi = (pid * bsp.shard_size, (pid + 1) * bsp.shard_size)

        def step(params, opt_state, model_state, rng, inp, tgt):
            t = counter["t"]
            grads, new_ms, loss = grad_step(params, model_state, rng,
                                            inp, tgt)
            gflat = np.asarray(ravel_pytree(grads)[0], np.float32)
            # aux scalars (loss, BN state) go out BEFORE the big gradient
            # blobs: when an owner drops this process's gradient at the
            # deadline, its loss/BN contribution is already visible, so
            # the books average over finished models (reference semantics)
            # instead of blocking behind the very puts that were dropped
            ms_flat = np.zeros(0, np.float32)
            ms_rebuild = None
            if n_proc > 1:
                bsp.publish_aux(t, "loss", np.float32(loss))
                ms_flat, ms_rebuild = self._float_leaf_pack(new_ms)
                if ms_flat.size:
                    bsp.publish_aux(t, "mstate", ms_flat)
            bsp.put_gradients(t, gflat)
            g_my, n_arrived, dropped = bsp.aggregate_my_partition(t)
            if dropped:
                self.metrics.add("dropped gradients", float(len(dropped)))
            if frozen_pad is not None:
                # zero frozen grads BEFORE the norm like the local/SPMD
                # paths, so l2 clipping sees the same global norm
                fr = frozen_pad[lo_hi[0]:lo_hi[1]]
                g_my = np.where(fr, 0.0, g_my)
            if l2_clip is not None:
                # global L2 norm needs every owner's partial square sum —
                # an 8-byte aux exchange (owners are never dropped)
                bsp.publish_aux(t, "gnorm2",
                                np.float64(np.sum(g_my.astype(np.float64)
                                                  ** 2)))
                parts = bsp.gather_aux(t, "gnorm2", blocking=True)
                norm = float(np.sqrt(sum(float(v) for v in parts.values())))
                g_my = g_my * min(1.0, l2_clip / (norm + 1e-6))
            if const_clip is not None:
                g_my = np.clip(g_my, const_clip[0], const_clip[1])
            # my current weight slice, in the padded flat layout — reuse
            # last iteration's assembled vector instead of re-flattening
            # the whole tree on the host every step (first call and a
            # post-resume restore pass a fresh tree and recompute)
            if params is cache["params_ref"]:
                wpad = cache["wpad"]
            else:
                wpad = bsp._pad(
                    np.asarray(ravel_pytree(params)[0], np.float32))
            my_w = wpad[lo_hi[0]:lo_hi[1]]
            new_w, new_opt = upd(jnp.asarray(g_my), opt_state,
                                 jnp.asarray(my_w))
            new_w = np.asarray(new_w, np.float32)
            if frozen_pad is not None:
                new_w = np.where(fr, my_w, new_w)
            bsp.publish_weights(t + 1, new_w)
            wfull = bsp.get_weights(t + 1)
            new_params = unravel(jnp.asarray(wfull))
            cache["params_ref"] = new_params
            cache["wpad"] = bsp._pad(wfull)
            # BN running stats / loss: average across processes (the pmean
            # the SPMD modes do each step). These gathers run AFTER
            # get_weights(t+1) — a full barrier every live owner passes
            # only after publishing its aux for t (program order) — so a
            # non-blocking gather deterministically sees every live
            # process; averaging over the arrived subset is the fallback
            # for a peer dying mid-window, not a second straggler wait
            if n_proc > 1:
                if ms_rebuild is not None and ms_flat.size:
                    gathered = bsp.gather_aux(t, "mstate", blocking=False)
                    if gathered:
                        new_ms = ms_rebuild(np.mean(
                            np.stack(list(gathered.values())), axis=0))
                losses = bsp.gather_aux(t, "loss", blocking=False)
                if losses:
                    loss = np.float32(np.mean([float(v)
                                               for v in losses.values()]))
            counter["t"] = t + 1
            return new_params, new_opt, new_ms, loss

        # owner's optimizer slots: my slice only (ZeRO-1 by process)
        wpad0 = bsp._pad(np.asarray(flat0, np.float32))
        opt_state = optim.init_state(
            jnp.asarray(wpad0[lo_hi[0]:lo_hi[1]]))
        return step, params, opt_state, batch_sharding

    # -- Optimizer hooks ---------------------------------------------------

    def _prepare(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        params, model_state = self.model.params, self.model.state

        if self.parameter_mode == "blockstore":
            step, dev_params, opt_state, batch_sharding = \
                self._build_blockstore_step(params)
            self._n_devices = len(jax.local_devices())

            def place_batch_local(batch: MiniBatch):
                def put1(x):
                    if batch_sharding is not None:
                        return jax.device_put(x, batch_sharding)
                    return jax.device_put(np.asarray(x))

                def put(x):
                    if isinstance(x, (list, tuple)):
                        return [put1(v) for v in x]
                    return put1(x)

                if batch_sharding is not None and \
                        batch.size() % self._n_devices != 0:
                    raise ValueError(
                        f"local batch {batch.size()} must divide the "
                        f"{self._n_devices}-chip local data axis")
                return put(batch.get_input()), put(batch.get_target())

            return step, place_batch_local, dev_params, opt_state, model_state

        mesh = self.mesh()
        self._n_devices = mesh.devices.size

        if self.parameter_mode == "partitioned":
            step, dev_params, opt_state = self._build_partitioned_step(mesh, params)
        else:
            step, dev_params, opt_state = self._build_allreduce_step(mesh, params)

        batch_sharding = NamedSharding(mesh, P("data"))
        n_proc = jax.process_count()

        def place_batch(batch: MiniBatch):
            def put1(x):
                if n_proc > 1:
                    # each process holds ITS rows of the global batch —
                    # assemble the global array from process-local shards
                    # (the pod analog of the reference's per-executor
                    # partition feed)
                    return jax.make_array_from_process_local_data(
                        batch_sharding, np.asarray(x))
                return jax.device_put(x, batch_sharding)

            def put(x):
                if isinstance(x, (list, tuple)):
                    return [put1(v) for v in x]
                return put1(x)

            inp, tgt = batch.get_input(), batch.get_target()
            if (batch.size() * n_proc) % self._n_devices != 0:
                raise ValueError(
                    f"global batch {batch.size() * n_proc} must divide the "
                    f"{self._n_devices}-chip data axis"
                )
            return put(inp), put(tgt)

        return step, place_batch, dev_params, opt_state, model_state

    def set_validation(self, trigger, dataset=None, methods=None,
                       batch_size=None, **kw):
        """Same GLOBAL batch-size semantics as training: in a pod each
        process evaluates 1/n_proc-sized local batches of it. Handles both
        the Scala order and the pyspark int-first order BEFORE dividing."""
        import jax

        n_proc = jax.process_count()
        if n_proc > 1:
            # normalize the pyspark positional order (batch_size, val_rdd,
            # trigger, val_method) to Scala order BEFORE dividing/checking
            # — the base class does this same int-first swap
            if isinstance(trigger, int):
                batch_size, dataset, trigger, methods = (
                    trigger, dataset, methods, batch_size)
            if batch_size is not None:
                if batch_size % n_proc:
                    raise ValueError(
                        f"global validation batch {batch_size} must divide "
                        f"the {n_proc}-process topology")
                batch_size //= n_proc
            # the pod merge collective needs a zero accumulator from
            # empty-shard processes — fail EARLY and on every process if a
            # custom method can't provide one (a late failure on one
            # process would hang the others in the all-gather)
            for m in list(methods or []) + list(kw.get("val_method") or []):
                if getattr(m, "_result_cls", None) is None:
                    raise ValueError(
                        f"{type(m).__name__} needs _result_cls set for pod "
                        "validation (see ValidationMethod.empty_result)")
        return super().set_validation(trigger, dataset, methods,
                                      batch_size, **kw)

    def _run_validation(self, params, model_state, state):
        """Pod runs: validation batches are process-local and per-process
        DIFFERENT, so they cannot feed the global-mesh eval step — gather
        params to host ONCE and let each process score its own shard with
        the local eval step; the per-method results merge globally in the
        base loop (ValidationResult.merge_across_processes)."""
        import jax

        if jax.process_count() > 1:
            params = self._ckpt_params_to_host(params)
            self._mh_eval = True
            try:
                return super()._run_validation(params, model_state, state)
            finally:
                self._mh_eval = False
        return super()._run_validation(params, model_state, state)

    def _eval_forward(self, params, model_state, inp):
        """Sharded in-training validation: batch split over the ``data``
        axis, every chip runs the forward (reference ``Evaluator.scala``'s
        distributed eval — SURVEY §3.3). In partitioned mode the full
        weights are reconstituted from the ARP shards *inside* the compiled
        program (one all_gather over ICI), never on the host."""
        import jax
        from jax.sharding import PartitionSpec as P

        if getattr(self, "_mh_eval", False) or \
                self.parameter_mode == "blockstore":
            # blockstore mode keeps full params per process and process-
            # local validation shards: score locally, merge in the driver
            # (ValidationResult.merge_across_processes)
            return Optimizer._eval_forward(self, params, model_state, inp)

        from bigdl_tpu.optim.evaluator import (
            make_sharded_eval_step, pad_shard_call,
        )

        if not hasattr(self, "_dist_eval_step"):
            mesh = self.mesh()
            if self.parameter_mode == "partitioned":
                arp, model = self._arp, self.model
                dev_pre = self._device_preprocess

                def spmd(shards, model_state, x):
                    if dev_pre is not None:
                        x = dev_pre(x)
                    p_full = arp.get_weights(shards[0])
                    out, _ = model.apply(p_full, x, model_state,
                                         training=False, rng=None)
                    return out

                from bigdl_tpu.utils.compat import shard_map

                self._dist_eval_step = jax.jit(shard_map(
                    spmd, mesh=mesh,
                    in_specs=(P("data"), P(), P("data")),
                    out_specs=P("data"),
                ))
            else:
                self._dist_eval_step = make_sharded_eval_step(
                    self.model, mesh,
                    device_preprocess=self._device_preprocess)
        return pad_shard_call(self._dist_eval_step, self._n_devices,
                              params, model_state, inp)

    def _ckpt_params_to_host(self, params):
        if self.parameter_mode == "partitioned":
            return self._arp.to_full(params)
        return params

    def _ckpt_opt_state_to_host(self, opt_state):
        """Partitioned mode: every opt-state leaf is (n, ...) sharded over
        'data' — in a pod those arrays span non-addressable devices, so
        gather each to a full host copy (the slot analog of to_full)."""
        import jax

        if self.parameter_mode != "partitioned":
            return opt_state

        def to_host(leaf):
            if getattr(leaf, "is_fully_addressable", True) is False and \
                    not getattr(leaf, "is_fully_replicated", False):
                from jax.experimental import multihost_utils

                return multihost_utils.process_allgather(leaf, tiled=True)
            return np.asarray(leaf)

        return jax.tree_util.tree_map(to_host, opt_state)

    def _opt_state_to_device(self, opt_state):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.parameter_mode != "partitioned":
            return opt_state
        sh = NamedSharding(self.mesh(), P("data"))
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(np.asarray(leaf), sh), opt_state)

    def _host_params_to_device(self, params):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.parameter_mode == "partitioned":
            shards = self._arp.init_shards(params)
            return jax.device_put(shards, NamedSharding(self.mesh(), P("data")))
        return params

    def _writeback(self, params, opt_state, model_state) -> None:
        import jax

        host_params = self._ckpt_params_to_host(params)
        self.model.params = jax.tree_util.tree_map(np.asarray, host_params)
        self.model.state = jax.tree_util.tree_map(np.asarray, model_state)
        self._final_opt_state = opt_state
