"""Vision pipeline — the reference's ``transform/vision/image`` surface.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/transform/vision/image/``
(later 0.x) — ``ImageFeature`` (a mutable map carrying the decoded mat,
label, uri, and derived tensors), ``ImageFrame.read``/``LocalImageFrame``,
``FeatureTransformer`` chained with ``->``, and the augmentation set
(``Resize``, ``CenterCrop``, ``RandomCrop``, ``HFlip``, ``Brightness``,
``Contrast``, ``Saturation``, ``Hue``, ``ChannelNormalize``,
``MatToTensor``, ``ImageFrameToSample``) backed by OpenCV JNI.

TPU-native redesign: images are numpy HWC float32 arrays on the host (the
``OpenCVMat`` role; decode via PIL, resize via the native C++ bilinear op
when available), transformers are tiny pure functions over the
``ImageFeature`` map composed with ``>>``, and the terminal
``ImageFrameToSample`` hands CHW tensors to the ``DataSet``/``Optimizer``
plane. All randomness is drawn from a seeded per-frame generator, so
pipelines are reproducible.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ImageFeature(dict):
    """Mutable feature map (reference ``ImageFeature``): well-known keys
    ``mat`` (HWC float32), ``label``, ``uri``, ``sample``."""

    MAT = "mat"
    LABEL = "label"
    URI = "uri"
    SAMPLE = "sample"

    def __init__(self, mat=None, label=None, uri: Optional[str] = None) -> None:
        super().__init__()
        if mat is not None:
            self[self.MAT] = np.asarray(mat, np.float32)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    def mat(self) -> np.ndarray:
        return self[self.MAT]

    def set_mat(self, m: np.ndarray) -> None:
        self[self.MAT] = np.asarray(m, np.float32)


class FeatureTransformer:
    """One step of the pipeline; compose with ``>>`` (the reference's
    ``->``). Subclasses override :meth:`transform_mat` (the common case) or
    :meth:`apply_feature` for whole-feature edits."""

    def apply_feature(self, feature: ImageFeature,
                      rng: np.random.RandomState) -> ImageFeature:
        feature.set_mat(self.transform_mat(feature.mat(), rng))
        return feature

    def transform_mat(self, mat: np.ndarray,
                      rng: np.random.RandomState) -> np.ndarray:
        return mat

    def __rshift__(self, other: "FeatureTransformer") -> "Pipeline":
        return Pipeline([self, other])

    def __call__(self, feature: ImageFeature,
                 rng: Optional[np.random.RandomState] = None) -> ImageFeature:
        return self.apply_feature(feature, rng or np.random.RandomState(0))


class Pipeline(FeatureTransformer):
    def __init__(self, stages: Sequence[FeatureTransformer]) -> None:
        self.stages = list(stages)

    def apply_feature(self, feature, rng):
        for s in self.stages:
            feature = s.apply_feature(feature, rng)
        return feature

    def __rshift__(self, other):
        return Pipeline(self.stages + [other])


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------

def _resize_hwc(mat: np.ndarray, h: int, w: int) -> np.ndarray:
    from bigdl_tpu.dataset.image import resize_bilinear

    return resize_bilinear(
        np.ascontiguousarray(mat.transpose(2, 0, 1)), h, w).transpose(1, 2, 0)


class Resize(FeatureTransformer):
    def __init__(self, resize_h: int, resize_w: int) -> None:
        self.h, self.w = resize_h, resize_w

    def transform_mat(self, mat, rng):
        return _resize_hwc(mat, self.h, self.w)


class AspectScale(FeatureTransformer):
    """Scale the SHORT side to ``min_size`` keeping aspect (reference
    ``AspectScale``)."""

    def __init__(self, min_size: int, max_size: int = 1000) -> None:
        self.min_size, self.max_size = min_size, max_size

    def transform_mat(self, mat, rng):
        h, w = mat.shape[:2]
        scale = self.min_size / min(h, w)
        if max(h, w) * scale > self.max_size:
            scale = self.max_size / max(h, w)
        return _resize_hwc(mat, max(1, round(h * scale)),
                           max(1, round(w * scale)))


class CenterCrop(FeatureTransformer):
    def __init__(self, crop_width: int, crop_height: int) -> None:
        self.w, self.h = crop_width, crop_height

    def transform_mat(self, mat, rng):
        H, W = mat.shape[:2]
        oy, ox = (H - self.h) // 2, (W - self.w) // 2
        return mat[oy:oy + self.h, ox:ox + self.w]


class RandomCrop(FeatureTransformer):
    def __init__(self, crop_width: int, crop_height: int) -> None:
        self.w, self.h = crop_width, crop_height

    def transform_mat(self, mat, rng):
        H, W = mat.shape[:2]
        oy = rng.randint(0, H - self.h + 1)
        ox = rng.randint(0, W - self.w + 1)
        return mat[oy:oy + self.h, ox:ox + self.w]


class HFlip(FeatureTransformer):
    """Horizontal flip with probability ``p`` (reference ``HFlip`` is
    unconditional; ``RandomTransformer(HFlip(), 0.5)`` is the random form —
    both shapes supported via ``p``)."""

    def __init__(self, p: float = 1.0) -> None:
        self.p = p

    def transform_mat(self, mat, rng):
        if self.p >= 1.0 or rng.rand() < self.p:
            return mat[:, ::-1].copy()
        return mat


class Expand(FeatureTransformer):
    """Zero-pad to a random larger canvas (reference ``Expand``, SSD aug)."""

    def __init__(self, max_expand_ratio: float = 2.0,
                 means: Sequence[float] = (123.0, 117.0, 104.0)) -> None:
        self.max_ratio = max_expand_ratio
        self.means = np.asarray(means, np.float32)

    def transform_mat(self, mat, rng):
        ratio = rng.uniform(1.0, self.max_ratio)
        H, W, C = mat.shape
        nh, nw = int(H * ratio), int(W * ratio)
        oy = rng.randint(0, nh - H + 1)
        ox = rng.randint(0, nw - W + 1)
        canvas = np.empty((nh, nw, C), np.float32)
        canvas[:] = self.means[:C]
        canvas[oy:oy + H, ox:ox + W] = mat
        return canvas


# ---------------------------------------------------------------------------
# photometric
# ---------------------------------------------------------------------------

class Brightness(FeatureTransformer):
    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0) -> None:
        self.lo, self.hi = delta_low, delta_high

    def transform_mat(self, mat, rng):
        return mat + rng.uniform(self.lo, self.hi)


class Contrast(FeatureTransformer):
    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5) -> None:
        self.lo, self.hi = delta_low, delta_high

    def transform_mat(self, mat, rng):
        return mat * rng.uniform(self.lo, self.hi)


class Saturation(FeatureTransformer):
    """Blend with the per-pixel grey value (channel mean)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5) -> None:
        self.lo, self.hi = delta_low, delta_high

    def transform_mat(self, mat, rng):
        f = rng.uniform(self.lo, self.hi)
        grey = mat.mean(axis=2, keepdims=True)
        return grey + (mat - grey) * f


class Hue(FeatureTransformer):
    """Rotate channels toward their mean by a random angle-ish factor (a
    cheap OpenCV-free hue shift: blend of channel roll)."""

    def __init__(self, delta: float = 18.0) -> None:
        self.delta = delta

    def transform_mat(self, mat, rng):
        f = rng.uniform(-self.delta, self.delta) / 180.0
        rolled = np.roll(mat, 1, axis=2)
        return mat * (1.0 - abs(f)) + rolled * abs(f)


class ChannelOrder(FeatureTransformer):
    """BGR↔RGB flip (reference ``ChannelOrder`` randomly shuffles; here the
    deterministic reverse, the common use)."""

    def transform_mat(self, mat, rng):
        return mat[:, :, ::-1].copy()


class ChannelNormalize(FeatureTransformer):
    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0,
                 std_b: float = 1.0) -> None:
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.std = np.asarray([std_r, std_g, std_b], np.float32)

    def transform_mat(self, mat, rng):
        return (mat - self.mean) / self.std


class PixelNormalizer(FeatureTransformer):
    """Subtract a full per-pixel mean image (reference ``PixelNormalizer``)."""

    def __init__(self, means: np.ndarray) -> None:
        self.means = np.asarray(means, np.float32)

    def transform_mat(self, mat, rng):
        return mat - self.means


class RandomTransformer(FeatureTransformer):
    """Apply ``inner`` with probability ``p`` (reference
    ``RandomTransformer``)."""

    def __init__(self, inner: FeatureTransformer, p: float) -> None:
        self.inner = inner
        self.p = p

    def apply_feature(self, feature, rng):
        if rng.rand() < self.p:
            return self.inner.apply_feature(feature, rng)
        return feature


# ---------------------------------------------------------------------------
# terminal stages
# ---------------------------------------------------------------------------

class MatToTensor(FeatureTransformer):
    """HWC float mat → CHW float32 tensor under ``to_key`` (reference
    ``MatToTensor`` / ``MatToFloats``)."""

    def __init__(self, to_key: str = "floats") -> None:
        self.to_key = to_key

    def apply_feature(self, feature, rng):
        feature[self.to_key] = np.ascontiguousarray(
            feature.mat().transpose(2, 0, 1), np.float32)
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Build the training ``Sample`` from feature keys (reference
    ``ImageFrameToSample(inputKeys, targetKeys)``)."""

    def __init__(self, input_keys: Sequence[str] = ("floats",),
                 target_keys: Optional[Sequence[str]] = ("label",)) -> None:
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys or [])

    def apply_feature(self, feature, rng):
        from bigdl_tpu.dataset.sample import Sample

        feats = [np.asarray(feature[k], np.float32) for k in self.input_keys]
        labels = [np.asarray(feature[k], np.float32)
                  for k in self.target_keys if k in feature]
        feature[ImageFeature.SAMPLE] = Sample(
            feats if len(feats) > 1 else feats[0],
            (labels if len(labels) > 1 else labels[0]) if labels else None)
        return feature


# ---------------------------------------------------------------------------
# ImageFrame
# ---------------------------------------------------------------------------

class LocalImageFrame:
    """In-memory collection of ImageFeatures (reference ``LocalImageFrame``);
    ``transform`` applies a FeatureTransformer chain to every feature with a
    per-feature seeded generator."""

    def __init__(self, features: List[ImageFeature], seed: int = 0) -> None:
        self.features = list(features)
        self.seed = seed

    def transform(self, transformer: FeatureTransformer) -> "LocalImageFrame":
        out = []
        for i, f in enumerate(self.features):
            rng = np.random.RandomState(self.seed * 1_000_003 + i)
            nf = ImageFeature()
            nf.update(f)
            out.append(transformer.apply_feature(nf, rng))
        return LocalImageFrame(out, self.seed)

    __rshift__ = transform

    def get_sample(self):
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def get_image(self):
        return [f.mat() for f in self.features]

    def get_label(self):
        return [f.get(ImageFeature.LABEL) for f in self.features]

    def __len__(self) -> int:
        return len(self.features)


class ImageFrame:
    """Factory facade (reference ``object ImageFrame``)."""

    @staticmethod
    def read(path: str, seed: int = 0) -> LocalImageFrame:
        """Read a file or directory of images (PIL decode, float32 HWC)."""
        from PIL import Image

        paths = []
        if os.path.isdir(path):
            for root, _, files in os.walk(path):
                for f in sorted(files):
                    if f.lower().endswith(
                            (".jpg", ".jpeg", ".png", ".bmp", ".gif")):
                        paths.append(os.path.join(root, f))
        else:
            paths = [path]
        feats = []
        for p in paths:
            with Image.open(p) as im:
                arr = np.asarray(im.convert("RGB"), np.float32)
            feats.append(ImageFeature(arr, uri=p))
        return LocalImageFrame(feats, seed)

    @staticmethod
    def array(mats: Sequence[np.ndarray], labels: Optional[Sequence] = None,
              seed: int = 0) -> LocalImageFrame:
        feats = []
        for i, m in enumerate(mats):
            feats.append(ImageFeature(
                m, None if labels is None else labels[i]))
        return LocalImageFrame(feats, seed)
