from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, DataSet, DistributedDataSet, LocalDataSet,
)
from bigdl_tpu.dataset.sample import MiniBatch, Sample, stack_samples
from bigdl_tpu.dataset.transformer import (
    ChainedTransformer, FnTransformer, SampleToBatch, SampleToMiniBatch,
    Transformer,
)
from bigdl_tpu.dataset.text import (
    Dictionary, LabeledSentence, LabeledSentenceToSample, SentenceToWordIndices,
    SequenceWindower, TextToLabeledSentence, simple_tokenize,
)

__all__ = [
    "AbstractDataSet", "DataSet", "DistributedDataSet", "LocalDataSet",
    "MiniBatch", "Sample", "stack_samples", "ChainedTransformer",
    "FnTransformer", "SampleToBatch", "SampleToMiniBatch", "Transformer",
    "Dictionary", "LabeledSentence", "LabeledSentenceToSample",
    "SentenceToWordIndices", "SequenceWindower", "TextToLabeledSentence",
    "simple_tokenize",
]
