"""Image transformers.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dataset/image/*.scala`` —
``BytesToBGRImg``, ``BGRImgNormalizer``, ``BGRImgCropper``, ``HFlip``,
``ColorJitter``, ``Lighting``, ``BGRImgToBatch``; the ResNet/Inception
ImageNet augmentation set, plus grey-image variants for MNIST.

Host-side numpy; images flow as ``Sample(feature=(C,H,W) float32, label)``.
Randomness uses per-transformer ``np.random.RandomState`` — host pipeline,
not traced, matching the reference's executor-side RNG.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class GreyImgNormalizer(Transformer):
    """(x - mean) / std on single-channel images (reference MNIST pipeline)."""

    def __init__(self, mean: float, std: float) -> None:
        self.mean = mean
        self.std = std

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            yield Sample((s.feature() - self.mean) / self.std, s.labels[0])


class BGRImgNormalizer(Transformer):
    """Per-channel (x - mean) / std, channels-first (reference CIFAR/ImageNet)."""

    def __init__(self, means, stds) -> None:
        self.means = np.asarray(means, np.float32).reshape(-1, 1, 1)
        self.stds = np.asarray(stds, np.float32).reshape(-1, 1, 1)

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            yield Sample((s.feature() - self.means) / self.stds, s.labels[0])


class BGRImgCropper(Transformer):
    """Random (train) or center crop to (crop_h, crop_w) (reference
    ``BGRImgCropper``/``CropCenter``/``CropRandom``)."""

    def __init__(self, crop_width: int, crop_height: int,
                 crop_method: str = "random", seed: int = 0) -> None:
        self.cw = crop_width
        self.ch = crop_height
        self.method = crop_method
        self._rng = np.random.RandomState(seed)

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            img = s.feature()  # (C, H, W)
            _, h, w = img.shape
            if self.method == "random":
                y0 = self._rng.randint(0, h - self.ch + 1)
                x0 = self._rng.randint(0, w - self.cw + 1)
            else:
                y0 = (h - self.ch) // 2
                x0 = (w - self.cw) // 2
            yield Sample(img[:, y0:y0 + self.ch, x0:x0 + self.cw], s.labels[0])


class HFlip(Transformer):
    def __init__(self, threshold: float = 0.5, seed: int = 0) -> None:
        self.threshold = threshold
        self._rng = np.random.RandomState(seed)

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            img = s.feature()
            if self._rng.rand() < self.threshold:
                img = img[:, :, ::-1].copy()
            yield Sample(img, s.labels[0])


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (reference ``ColorJitter``, ResNet ImageNet recipe)."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4, seed: int = 0) -> None:
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self._rng = np.random.RandomState(seed)

    def _blend(self, a, b, alpha):
        return alpha * a + (1.0 - alpha) * b

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            img = s.feature().astype(np.float32)
            ops = [self._bright, self._contrast, self._saturate]
            self._rng.shuffle(ops)
            for op in ops:
                img = op(img)
            yield Sample(img, s.labels[0])

    def _bright(self, img):
        alpha = 1.0 + self.brightness * (2 * self._rng.rand() - 1)
        return self._blend(img, np.zeros_like(img), alpha)

    def _contrast(self, img):
        alpha = 1.0 + self.contrast * (2 * self._rng.rand() - 1)
        grey = img.mean()
        return self._blend(img, np.full_like(img, grey), alpha)

    def _saturate(self, img):
        alpha = 1.0 + self.saturation * (2 * self._rng.rand() - 1)
        grey = img.mean(axis=0, keepdims=True)
        return self._blend(img, np.broadcast_to(grey, img.shape), alpha)


class Lighting(Transformer):
    """AlexNet-style PCA lighting noise (reference ``Lighting``; uses the
    ImageNet eigendecomposition constants)."""

    _eigval = np.array([0.2175, 0.0188, 0.0045], np.float32)
    _eigvec = np.array(
        [[-0.5675, 0.7192, 0.4009],
         [-0.5808, -0.0045, -0.8140],
         [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd: float = 0.1, seed: int = 0) -> None:
        self.alphastd = alphastd
        self._rng = np.random.RandomState(seed)

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            img = s.feature().astype(np.float32)
            alpha = self._rng.randn(3).astype(np.float32) * self.alphastd
            shift = (self._eigvec @ (alpha * self._eigval)).reshape(3, 1, 1)
            yield Sample(img + shift, s.labels[0])


class RandomResizedCrop(Transformer):
    """Scale-and-aspect random crop then resize (Inception/ResNet train aug;
    reference vision pipeline's RandomCropper+Resize). Pure numpy bilinear."""

    def __init__(self, size: int, min_area: float = 0.08, seed: int = 0) -> None:
        self.size = size
        self.min_area = min_area
        self._rng = np.random.RandomState(seed)

    def apply(self, it: Iterator[Sample]) -> Iterator[Sample]:
        for s in it:
            img = s.feature()
            _, h, w = img.shape
            for _ in range(10):
                area = h * w * self._rng.uniform(self.min_area, 1.0)
                ratio = self._rng.uniform(3 / 4, 4 / 3)
                ch = int(round(np.sqrt(area / ratio)))
                cw = int(round(np.sqrt(area * ratio)))
                if ch <= h and cw <= w:
                    y0 = self._rng.randint(0, h - ch + 1)
                    x0 = self._rng.randint(0, w - cw + 1)
                    crop = img[:, y0:y0 + ch, x0:x0 + cw]
                    break
            else:
                side = min(h, w)
                crop = img[:, :side, :side]
            yield Sample(resize_bilinear(crop, self.size, self.size), s.labels[0])


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize on a (C, H, W) numpy image."""
    c, h, w = img.shape
    if h == out_h and w == out_w:
        return img.astype(np.float32)
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[None, :, None]
    wx = np.clip(xs - x0, 0, 1)[None, None, :]
    p00 = img[:, y0][:, :, x0]
    p01 = img[:, y0][:, :, x1]
    p10 = img[:, y1][:, :, x0]
    p11 = img[:, y1][:, :, x1]
    top = p00 * (1 - wx) + p01 * wx
    bot = p10 * (1 - wx) + p11 * wx
    return (top * (1 - wy) + bot * wy).astype(np.float32)


def image_folder_samples(path: str, image_size: int = 224):
    """Load an ImageFolder-style directory (class-per-subdir) into Samples.
    PNG/JPEG decode via PIL when available (reference used OpenCV JNI)."""
    import os

    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("PIL required for image_folder loading") from e

    classes = sorted(
        d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d))
    )
    samples = []
    for ci, cls in enumerate(classes):
        cdir = os.path.join(path, cls)
        for fname in sorted(os.listdir(cdir)):
            img = Image.open(os.path.join(cdir, fname)).convert("RGB")
            arr = np.asarray(img, np.float32).transpose(2, 0, 1) / 255.0
            arr = resize_bilinear(arr, image_size, image_size)
            samples.append(Sample(arr, np.float32(ci + 1)))  # 1-based label
    return samples
