"""MNIST loader.

Reference (UNVERIFIED, SURVEY.md §0): ``pyspark/bigdl/dataset/mnist.py`` —
idx-file download + parse. This sandbox has zero egress, so the loader reads
idx files from disk when present and otherwise falls back to a deterministic
synthetic digit set (class-dependent blob patterns) so the LeNet config runs
end-to-end anywhere.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import List, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN = 0.13066047740239436 * 255
TRAIN_STD = 0.30810780876661765 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024294290553 * 255


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), np.uint8)


def _synthetic_digits(n: int, seed: int,
                      noise: float = 25.0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable stand-in: each class is a distinct 28x28
    blob pattern plus noise (``noise`` = std in 0..255 pixel units; high
    values make the accuracy-parity harness land below 100%, a sharper
    parity signal)."""
    rng = np.random.RandomState(seed)
    protos = np.zeros((10, 28, 28), np.float32)
    proto_rng = np.random.RandomState(1234)
    for c in range(10):
        for _ in range(4):
            cy, cx = proto_rng.randint(4, 24, 2)
            yy, xx = np.mgrid[0:28, 0:28]
            protos[c] += np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0)
    protos = protos / protos.max(axis=(1, 2), keepdims=True) * 255.0
    labels = rng.randint(0, 10, n)
    imgs = protos[labels] + rng.randn(n, 28, 28).astype(np.float32) * noise
    return np.clip(imgs, 0, 255).astype(np.uint8), labels.astype(np.uint8)


def read_data_sets(data_dir: str, kind: str = "train",
                   synthetic_fallback: bool = True,
                   synthetic_count: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images uint8 (N,28,28), labels uint8 0-9)."""
    prefix = "train" if kind == "train" else "t10k"
    candidates = [
        (f"{prefix}-images-idx3-ubyte", f"{prefix}-labels-idx1-ubyte"),
        (f"{prefix}-images-idx3-ubyte.gz", f"{prefix}-labels-idx1-ubyte.gz"),
    ]
    for img_name, lab_name in candidates:
        ip = os.path.join(data_dir, img_name)
        lp = os.path.join(data_dir, lab_name)
        if os.path.exists(ip) and os.path.exists(lp):
            return _read_idx_images(ip), _read_idx_labels(lp)
    if not synthetic_fallback:
        raise FileNotFoundError(f"no MNIST idx files under {data_dir}")
    seed = 7 if kind == "train" else 13
    return _synthetic_digits(synthetic_count, seed)


def write_idx_files(data_dir: str, images: np.ndarray, labels: np.ndarray,
                    kind: str = "train") -> None:
    """Write (N,28,28) uint8 images + uint8 labels as real MNIST idx files
    (the exact format ``read_data_sets`` parses). Used by the
    accuracy-parity harness to exercise the real-file reader path and by
    users converting their own digit datasets."""
    os.makedirs(data_dir, exist_ok=True)
    prefix = "train" if kind == "train" else "t10k"
    images = np.asarray(images)
    labels = np.asarray(labels)
    if images.dtype != np.uint8 or labels.dtype != np.uint8:
        raise ValueError(
            f"idx files store uint8; got images {images.dtype}, labels "
            f"{labels.dtype} — scale to [0, 255] and cast explicitly")
    if images.ndim != 3:
        raise ValueError(f"images must be (N, rows, cols); got {images.shape}")
    n, rows, cols = images.shape
    if len(labels) != n:
        raise ValueError(f"{n} images but {len(labels)} labels")
    images = np.ascontiguousarray(images)
    labels = np.ascontiguousarray(labels)
    with open(os.path.join(data_dir, f"{prefix}-images-idx3-ubyte"), "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(images.tobytes())
    with open(os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte"), "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())


def generate_idx_dataset(data_dir: str, n_train: int = 4096,
                         n_test: int = 1024, seed: int = 7,
                         noise: float = 25.0) -> None:
    """Generate a deterministic LEARNABLE digit dataset as real idx files
    on disk (train + t10k pairs) — the in-env stand-in for downloading
    MNIST (zero egress), feeding the real reader path end to end."""
    write_idx_files(data_dir, *_synthetic_digits(n_train, seed, noise),
                    "train")
    write_idx_files(data_dir, *_synthetic_digits(n_test, seed + 6, noise),
                    "test")


def load_samples(data_dir: str, kind: str = "train", **kw) -> List[Sample]:
    """Samples with (1,28,28) float features and 1-based labels, the shape
    the reference LeNet pipeline produces."""
    imgs, labels = read_data_sets(data_dir, kind, **kw)
    return [
        Sample(imgs[i].astype(np.float32)[None, :, :], np.float32(labels[i] + 1))
        for i in range(len(imgs))
    ]
