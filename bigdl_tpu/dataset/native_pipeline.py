"""MiniBatch pipeline backed by the native C++ prefetch executor.

Reference (UNVERIFIED, SURVEY.md §0): the reference's hot image path is
OpenCV-JNI decode/augment on ``Engine.default`` ThreadPool threads feeding
``SampleToMiniBatch`` (``.../dataset/image/*.scala``,
``.../utils/ThreadPool.scala``). This module is the TPU-host analog: raw
uint8 images stay in one NHWC array, a background thread draws augmentation
randomness and pushes batch jobs into :class:`bigdl_tpu.native.NativeLoader`
(C++ worker pool, off-GIL), and the training loop pops finished float32
CHW batches — augmentation overlaps device compute.

Falls back to an equivalent pure-numpy iterator when the toolchain is
missing (``bigdl_tpu.native.is_available()`` False).
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional, Sequence

import numpy as np

import bigdl_tpu.native as native
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch


class NativeImagePipeline(AbstractDataSet):
    """Iterates MiniBatches from (N, H, W, C) uint8 images + int labels.

    train=True: infinite shuffled stream, random crop + hflip.
    train=False: one pass, center crop, no flip.
    Crop padding (pad then random-crop, the reference CIFAR recipe) is
    supported via ``pad``.
    """

    def __init__(self, images: np.ndarray, labels: Sequence[int], *,
                 batch_size: int, crop: Optional[tuple] = None,
                 mean, std, pad: int = 0, hflip: bool = True,
                 queue_depth: int = 4, n_workers: int = 4,
                 seed: int = 0, output: str = "f32_nchw") -> None:
        if output not in ("f32_nchw", "u8_nhwc"):
            raise ValueError(f"unknown output {output!r}")
        images = np.ascontiguousarray(images, np.uint8)
        assert images.ndim == 4, "expect (N, H, W, C) uint8"
        if pad:
            images = np.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        self.images = images
        self.labels = np.ascontiguousarray(labels, np.int32)
        self.n, self.h, self.w, self.c = images.shape
        self.crop_h, self.crop_w = crop if crop else (self.h, self.w)
        if self.crop_h > self.h or self.crop_w > self.w:
            raise ValueError(
                f"crop {self.crop_h}x{self.crop_w} exceeds (padded) image "
                f"{self.h}x{self.w}")
        self.batch = batch_size
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.hflip = hflip
        self.queue_depth = queue_depth
        self.n_workers = n_workers
        self.seed = seed
        self.output = output

    def size(self) -> int:
        return self.n

    # -- index/param generation (host RNG stays in Python, §5.2 analog) --

    def _epoch_indices(self, rng: np.random.RandomState, train: bool):
        idx = np.arange(self.n)
        if train:
            rng.shuffle(idx)
        return idx

    def _params(self, rng: np.random.RandomState, train: bool, k: int):
        max_y = self.h - self.crop_h
        max_x = self.w - self.crop_w
        if train:
            oy = rng.randint(0, max_y + 1, k).astype(np.int32)
            ox = rng.randint(0, max_x + 1, k).astype(np.int32)
            fl = (rng.rand(k) < 0.5).astype(np.uint8) if self.hflip else \
                np.zeros(k, np.uint8)
        else:
            oy = np.full(k, max_y // 2, np.int32)
            ox = np.full(k, max_x // 2, np.int32)
            fl = np.zeros(k, np.uint8)
        return oy, ox, fl

    # -- iteration --

    def data(self, train: bool) -> Iterator[MiniBatch]:
        if self.output == "u8_nhwc":
            # host does crop/flip COPIES only (uint8, no float conversion,
            # no transpose): quarter the transfer bytes, and the heavy
            # normalize runs on device (DeviceImageNormalizer inside the
            # jitted step). The C++ loader is pointless here — the hot
            # work moved off the host
            return self._u8_iter(train)
        if native.is_available():
            return self._native_iter(train)
        return self._numpy_iter(train)

    def device_normalizer(self):
        """The matching on-device preprocess for ``output="u8_nhwc"``
        batches (pass to ``Optimizer.set_device_preprocess`` /
        ``make_train_step(device_preprocess=...)``)."""
        return DeviceImageNormalizer(self.mean, self.std)

    def _u8_iter(self, train: bool) -> Iterator[MiniBatch]:
        return self._host_iter(train, u8=True)

    def _numpy_iter(self, train: bool) -> Iterator[MiniBatch]:
        return self._host_iter(train, u8=False)

    def _host_iter(self, train: bool, u8: bool) -> Iterator[MiniBatch]:
        """ONE epoch/shuffle/crop/flip loop for both host feeds — only the
        per-image finishing differs (u8 passthrough vs normalize+CHW), so
        the two cannot drift apart."""
        rng = np.random.RandomState(self.seed)
        while True:
            idx = self._epoch_indices(rng, train)
            for i in range(0, self.n - self.batch + 1, self.batch):
                sel = idx[i:i + self.batch]
                oy, ox, fl = self._params(rng, train, len(sel))
                if u8:
                    out = np.empty(
                        (len(sel), self.crop_h, self.crop_w, self.c),
                        np.uint8)
                else:
                    out = np.empty(
                        (len(sel), self.c, self.crop_h, self.crop_w),
                        np.float32)
                for j, s in enumerate(sel):
                    im = self.images[s, oy[j]:oy[j] + self.crop_h,
                                     ox[j]:ox[j] + self.crop_w, :]
                    if fl[j]:
                        im = im[:, ::-1, :]
                    if u8:
                        out[j] = im
                    else:
                        out[j] = ((im.astype(np.float32) - self.mean) /
                                  self.std).transpose(2, 0, 1)
                yield MiniBatch(out, self.labels[sel].astype(np.float32))
            if not train:
                return

    def _native_iter(self, train: bool) -> Iterator[MiniBatch]:
        loader = native.NativeLoader(
            self.batch, self.h, self.w, self.c, self.crop_h, self.crop_w,
            self.mean, self.std, queue_depth=self.queue_depth,
            n_workers=self.n_workers)
        rng = np.random.RandomState(self.seed)
        n_batches_per_epoch = self.n // self.batch
        stop = threading.Event()

        def producer():
            try:
                while not stop.is_set():
                    idx = self._epoch_indices(rng, train)
                    for i in range(n_batches_per_epoch):
                        if stop.is_set():
                            return
                        sel = idx[i * self.batch:(i + 1) * self.batch]
                        oy, ox, fl = self._params(rng, train, len(sel))
                        loader.push(self.images[sel], self.labels[sel],
                                    oy, ox, fl)
                    if not train:
                        return
            except RuntimeError:
                pass  # loader closed under us — consumer is done

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            if train:
                while True:
                    out, lab = loader.pop()
                    yield MiniBatch(out, lab.astype(np.float32))
            else:
                for _ in range(n_batches_per_epoch):
                    out, lab = loader.pop()
                    yield MiniBatch(out, lab.astype(np.float32))
        finally:
            stop.set()
            loader.stop()       # unblock a producer stuck in push()
            t.join(timeout=5)
            loader.close()      # frees only after no thread can touch it


class DeviceImageNormalizer:
    """uint8 NHWC batch → normalized float32 NCHW, traced inside the jitted
    train step (the device-side half of the ``output="u8_nhwc"`` feed)."""

    def __init__(self, mean, std) -> None:
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, x):
        import jax.numpy as jnp

        xf = (x.astype(jnp.float32) - self.mean) / self.std
        return jnp.transpose(xf, (0, 3, 1, 2))
