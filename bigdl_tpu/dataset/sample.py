"""Sample / MiniBatch — the unit records of the input pipeline.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dataset/Sample.scala``
(``ArraySample``: contiguous feature+label storage), ``MiniBatch.scala``
(``slice`` for per-thread sub-batches), ``SampleToMiniBatch.scala``.

TPU-native: numpy on the host side (pipeline runs on CPU feeding the chips);
a ``MiniBatch`` is the host-side staging buffer that the optimizer
``device_put``s with the mesh sharding — batch slicing for "sub-models"
disappears (XLA uses the whole chip) but ``slice`` is kept for parity and for
the data-parallel shard math.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Union

import numpy as np


def _to_np(x) -> np.ndarray:
    if isinstance(x, np.ndarray):
        return x
    try:
        from bigdl_tpu.tensor import Tensor

        if isinstance(x, Tensor):
            return x.to_numpy()
    except ImportError:  # pragma: no cover
        pass
    return np.asarray(x)


class Sample:
    """One training record: feature tensor(s) + label tensor(s)."""

    def __init__(self, features: Any, labels: Any) -> None:
        if isinstance(features, (list, tuple)):
            self.features = [_to_np(f) for f in features]
            self._multi_feature = True
        else:
            self.features = [_to_np(features)]
            self._multi_feature = False
        if isinstance(labels, (list, tuple)):
            self.labels = [_to_np(l) for l in labels]
        else:
            self.labels = [_to_np(labels)]

    def feature(self, i: int = 0) -> np.ndarray:
        return self.features[i]

    def label(self, i: int = 0) -> np.ndarray:
        return self.labels[i]

    def __repr__(self) -> str:
        fs = ",".join(str(f.shape) for f in self.features)
        ls = ",".join(str(l.shape) for l in self.labels)
        return f"Sample(features=[{fs}], labels=[{ls}])"


class MiniBatch:
    """A batched group of samples: stacked input + target arrays."""

    def __init__(self, input: Any, target: Any = None) -> None:
        self.input = input
        self.target = target

    def size(self) -> int:
        x = self.input[0] if isinstance(self.input, (list, tuple)) else self.input
        return x.shape[0]

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """1-based offset, reference-style."""
        s = slice(offset - 1, offset - 1 + length)

        def cut(x):
            if isinstance(x, (list, tuple)):
                return [v[s] for v in x]
            return x[s] if x is not None else None

        return MiniBatch(cut(self.input), cut(self.target))

    def __repr__(self) -> str:
        return f"MiniBatch(size={self.size()})"


def stack_samples(samples: Sequence[Sample]) -> MiniBatch:
    """Stack samples into one MiniBatch (the SampleToMiniBatch kernel)."""
    n_feat = len(samples[0].features)
    n_lab = len(samples[0].labels)
    feats = [np.stack([s.features[i] for s in samples]) for i in range(n_feat)]
    labs = [np.stack([s.labels[i] for s in samples]) for i in range(n_lab)]
    inp = feats[0] if n_feat == 1 else feats
    tgt = labs[0] if n_lab == 1 else labs
    return MiniBatch(inp, tgt)
