"""Sharded record-file ingestion — the ImageNet-at-scale input path.

Reference (UNVERIFIED, SURVEY.md §0): ``DataSet.SeqFileFolder`` — ImageNet
packed into Hadoop SequenceFiles (key = label, value = encoded image bytes),
one file per shard, read partition-parallel by Spark executors
(``.../dataset/DataSet.scala — SeqFileFolder``).

TPU-native redesign: Hadoop is gone; the same role is a directory of
**record shards** — a dead-simple length-prefixed binary format
(``RECS`` magic, then per record: varint label, varint payload length,
payload bytes) written once by :func:`write_shards` and consumed by
``DataSet.seq_file_folder``:

* shard list split round-robin across processes (``shard_index`` /
  ``num_shards`` — the per-host sharding of a pod job, mirroring one Spark
  partition per executor);
* per-epoch shard-order + intra-shard shuffling (train), sequential (eval);
* decode on the host via the C++ native pipeline when available, feeding
  device batches — the reference's OpenCV role (SURVEY.md §2.1).
"""

from __future__ import annotations

import io
import os
import struct
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.dataset import LocalDataSet
from bigdl_tpu.dataset.sample import Sample

MAGIC = b"RECS"


def _write_varint(f, x: int) -> None:
    if x < 0:
        raise ValueError(f"varint fields must be non-negative, got {x}")
    while True:
        b = x & 0x7F
        x >>= 7
        f.write(bytes([b | 0x80] if x else [b]))
        if not x:
            return


def _read_varint(f) -> Optional[int]:
    result, shift = 0, 0
    while True:
        c = f.read(1)
        if not c:
            return None
        b = c[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result
        shift += 7


def write_shards(records: Sequence[Tuple[int, bytes]], out_dir: str,
                 n_shards: int = 8, prefix: str = "part") -> List[str]:
    """Pack ``(label, payload)`` records into ``n_shards`` shard files
    (round-robin, like the reference's SequenceFile packing job)."""
    os.makedirs(out_dir, exist_ok=True)
    paths = [os.path.join(out_dir, f"{prefix}-{i:05d}.recs")
             for i in range(n_shards)]
    files = [open(p, "wb") for p in paths]
    try:
        for f in files:
            f.write(MAGIC)
        for i, (label, payload) in enumerate(records):
            f = files[i % n_shards]
            _write_varint(f, int(label))
            _write_varint(f, len(payload))
            f.write(payload)
    finally:
        for f in files:
            f.close()
    return paths


def read_shard(path: str) -> Iterator[Tuple[int, bytes]]:
    """Yield ``(label, payload)`` records. Uses the native C++ indexer when
    available (one pass over an in-memory buffer, ~100× the Python byte
    loop on big shards); pure-Python fallback otherwise."""
    try:
        from bigdl_tpu import native

        if native.is_available():
            buf = np.fromfile(path, np.uint8)
            try:
                labels, offsets, lengths = native.recs_index(buf)
            except ValueError as e:
                raise ValueError(f"{path}: {e}") from None
            # per-record bytes come straight off the mmap-able array — no
            # whole-shard second copy
            for lab, off, ln in zip(labels, offsets, lengths):
                yield int(lab), buf[off:off + ln].tobytes()
            return
    except OSError:
        pass  # no toolchain — fall through to the Python reader
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not a RECS shard")
        while True:
            label = _read_varint(f)
            if label is None:
                return
            ln = _read_varint(f)
            payload = f.read(ln)
            if len(payload) != ln:
                raise ValueError(f"{path}: truncated record")
            yield label, payload


def _default_decoder(label: int, payload: bytes) -> Sample:
    """Payload = raw float32 tensor bytes prefixed with a shape header
    (ndim u8, dims u32le each). Use ``decoder=`` for JPEG etc."""
    nd = payload[0]
    dims = struct.unpack_from(f"<{nd}I", payload, 1)
    arr = np.frombuffer(payload, np.float32, offset=1 + 4 * nd).reshape(dims)
    return Sample(arr.copy(), np.int32(label))


def encode_array(arr: np.ndarray) -> bytes:
    """Inverse of the default decoder's payload format."""
    arr = np.ascontiguousarray(arr, np.float32)
    header = bytes([arr.ndim]) + struct.pack(f"<{arr.ndim}I", *arr.shape)
    return header + arr.tobytes()


class SeqFileDataSet(LocalDataSet):
    """Shard-backed dataset with per-process shard assignment. Follows the
    LocalDataSet transformer-chain contract (``ds >> transformer``)."""

    def __init__(self, folder: str,
                 decoder: Optional[Callable[[int, bytes], Sample]] = None,
                 shard_index: int = 0, num_shards: int = 1,
                 seed: int = 0, transformers=None) -> None:
        self._folder = folder
        all_paths = sorted(
            os.path.join(folder, f) for f in os.listdir(folder)
            if f.endswith(".recs")
        )
        if not all_paths:
            raise ValueError(f"no .recs shards under {folder}")
        # round-robin shard→process assignment (one Spark partition per
        # executor ≙ one shard subset per TPU host process)
        self.paths = all_paths[shard_index::num_shards]
        if not self.paths:
            raise ValueError(
                f"process {shard_index}/{num_shards} gets no shards — "
                f"{folder} holds only {len(all_paths)} .recs files; write at "
                "least one shard per process")
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.decoder = decoder or _default_decoder
        self._seed = seed
        self._transformers = list(transformers or [])
        self._epoch = 0
        self._size: Optional[int] = None

    def size(self) -> int:
        if self._size is None:
            n = 0
            for p in self.paths:
                for _ in read_shard(p):
                    n += 1
            self._size = n
        return self._size

    def transform(self, transformer) -> "SeqFileDataSet":
        out = SeqFileDataSet(self._folder, self.decoder, self.shard_index,
                             self.num_shards, self._seed,
                             self._transformers + [transformer])
        return out

    __rshift__ = transform

    def _iter_once(self, shuffle: bool) -> Iterator[Sample]:
        rng = np.random.default_rng(self._seed + self._epoch)
        order = list(self.paths)
        if shuffle:
            rng.shuffle(order)
        for path in order:
            records = list(read_shard(path))
            if shuffle:
                rng.shuffle(records)
            for label, payload in records:
                yield self.decoder(label, payload)

    def _base_iter(self, train: bool) -> Iterator[Sample]:
        if not train:
            yield from self._iter_once(shuffle=False)
            return
        while True:
            yield from self._iter_once(shuffle=True)
            self._epoch += 1
