"""20-Newsgroups + GloVe loaders (the textclassifier config's data).

Reference (UNVERIFIED, SURVEY.md §0): ``pyspark/bigdl/dataset/news20.py`` —
``get_news20(dest_dir)`` downloads/expands the 20news-18828 archive into
``(text, label)`` pairs and ``get_glove_w2v(dest_dir, dim)`` yields GloVe
word vectors.

This sandbox has zero egress, so both loaders read pre-downloaded artifacts
from disk when present (the same archive/txt layouts the reference expects)
and otherwise fall back to a deterministic synthetic corpus/embedding so the
textclassifier config runs end-to-end anywhere.
"""

from __future__ import annotations

import os
import tarfile
from typing import Dict, Iterator, List, Tuple

import numpy as np

CLASS_NUM = 20


def _synthetic_news(n_per_class: int, seed: int) -> List[Tuple[str, int]]:
    """Learnable stand-in: each class has a distinct keyword vocabulary, so
    a bag-of-embeddings classifier can separate them."""
    rng = np.random.RandomState(seed)
    texts = []
    for c in range(CLASS_NUM):
        class_words = [f"topic{c}word{k}" for k in range(8)]
        shared = [f"common{k}" for k in range(16)]
        for _ in range(n_per_class):
            n_w = int(rng.randint(20, 60))
            words = [
                class_words[rng.randint(len(class_words))]
                if rng.rand() < 0.5 else shared[rng.randint(len(shared))]
                for _ in range(n_w)
            ]
            texts.append((" ".join(words), c + 1))  # 1-based labels
    return texts


def get_news20(dest_dir: str = "/tmp/news20",
               n_per_class: int = 25,
               seed: int = 42) -> List[Tuple[str, int]]:
    """Return ``[(text, 1-based label)]``. Reads an expanded
    ``20news-18828/`` tree (class-per-subdir of message files) or the
    ``.tar.gz`` archive from ``dest_dir`` when present; synthetic otherwise."""
    tree = os.path.join(dest_dir, "20news-18828")
    archive = None
    if os.path.isdir(dest_dir):
        for f in os.listdir(dest_dir):
            if f.startswith("20news") and f.endswith((".tar.gz", ".tgz")):
                archive = os.path.join(dest_dir, f)
                break
    if not os.path.isdir(tree) and archive is not None:
        with tarfile.open(archive, "r:gz") as tf:
            tf.extractall(dest_dir, filter="data")
    if os.path.isdir(tree):
        texts: List[Tuple[str, int]] = []
        for label, group in enumerate(sorted(os.listdir(tree)), start=1):
            gdir = os.path.join(tree, group)
            if not os.path.isdir(gdir):
                continue
            for fname in sorted(os.listdir(gdir)):
                path = os.path.join(gdir, fname)
                with open(path, "rb") as f:
                    texts.append((f.read().decode("latin1"), label))
        if texts:
            return texts
    return _synthetic_news(n_per_class, seed)


def _synthetic_glove(dim: int, seed: int) -> Iterator[Tuple[str, np.ndarray]]:
    """Deterministic per-word vectors (hash-seeded) covering the synthetic
    corpus vocabulary and any word asked of it via ``glove_lookup``."""
    rng = np.random.RandomState(seed)
    for c in range(CLASS_NUM):
        for k in range(8):
            w = f"topic{c}word{k}"
            yield w, rng.standard_normal(dim).astype(np.float32)
    for k in range(16):
        yield f"common{k}", rng.standard_normal(dim).astype(np.float32)


def get_glove_w2v(source_dir: str = "/tmp/news20/glove.6B", dim: int = 100,
                  seed: int = 42) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield ``(word, vector)`` pairs from ``glove.6B.<dim>d.txt`` when the
    file exists; synthetic vocabulary otherwise."""
    path = os.path.join(source_dir, f"glove.6B.{dim}d.txt")
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                yield parts[0], np.asarray(parts[1:], np.float32)
        return
    yield from _synthetic_glove(dim, seed)


def glove_dict(source_dir: str = "/tmp/news20/glove.6B", dim: int = 100,
               seed: int = 42) -> Dict[str, np.ndarray]:
    return dict(get_glove_w2v(source_dir, dim, seed))
