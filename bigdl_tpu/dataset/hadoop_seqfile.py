"""Hadoop SequenceFile ingestion — reference-format corpora read path.

Reference (UNVERIFIED, SURVEY.md §0): ``DataSet.SeqFileFolder``
(``.../dataset/DataSet.scala``) consumed ImageNet packed into Hadoop
SequenceFiles (key = ``org.apache.hadoop.io.Text`` label, value =
``BytesWritable`` image bytes), one file per shard. This framework's
native shard format is RECS (``dataset/seqfile.py``) — a TPU-host-friendly
redesign — but a reference user's EXISTING SequenceFile corpus needs a
read path, so this module provides:

* a pure-Python **reader** for uncompressed SequenceFiles (format
  version 4–6: record-level layout with sync markers; block/record
  compression raises with the codec name — no Hadoop-native codecs here);
* a **writer** producing files Hadoop itself can read (used by the tests
  and by packing jobs that want reference-format output);
* :func:`convert_to_recs` — one-pass conversion of a SequenceFile folder
  into RECS shards so the corpus rides the native indexer + the measured
  host pipeline afterwards;
* :class:`HadoopSeqFileDataSet` — direct streaming ingestion with the
  same shard-per-process round-robin contract as ``SeqFileDataSet``.

Writable codecs implemented: ``Text`` (vint length + utf8),
``BytesWritable`` (int32-BE length + raw), ``IntWritable``/
``LongWritable`` (fixed big-endian). The vint codec is Hadoop's
``WritableUtils.writeVLong`` encoding, bit-exact.
"""

from __future__ import annotations

import io
import os
import struct
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.dataset import LocalDataSet

_MAGIC = b"SEQ"
TEXT = "org.apache.hadoop.io.Text"
BYTES_WRITABLE = "org.apache.hadoop.io.BytesWritable"
INT_WRITABLE = "org.apache.hadoop.io.IntWritable"
LONG_WRITABLE = "org.apache.hadoop.io.LongWritable"


# -- Hadoop WritableUtils vint codec (bit-exact) ---------------------------

def write_vlong(f, v: int) -> None:
    if -112 <= v <= 127:
        f.write(struct.pack("b", v))
        return
    neg = v < 0
    if neg:
        v = ~v
    length, tmp = 0, v
    while tmp:
        length += 1
        tmp >>= 8
    f.write(struct.pack("b", (-120 - length) if neg else (-112 - length)))
    for i in range(length - 1, -1, -1):
        f.write(bytes([(v >> (8 * i)) & 0xFF]))


def read_vlong(f) -> int:
    raw = f.read(1)
    if not raw:
        raise EOFError("vint at EOF")
    (b,) = struct.unpack("b", raw)
    if b >= -112:
        return b
    neg = b < -120
    # Hadoop's decodeVIntSize counts the marker byte itself
    n_data = ((-119 - b) if neg else (-111 - b)) - 1
    v = 0
    for _ in range(n_data):
        v = (v << 8) | f.read(1)[0]
    return ~v if neg else v


def _write_hadoop_string(f, s: str) -> None:
    data = s.encode("utf-8")
    write_vlong(f, len(data))
    f.write(data)


def _read_hadoop_string(f) -> str:
    n = read_vlong(f)
    return f.read(n).decode("utf-8")


# -- Writable payload codecs ----------------------------------------------

def encode_text(s: str) -> bytes:
    buf = io.BytesIO()
    data = s.encode("utf-8")
    write_vlong(buf, len(data))
    buf.write(data)
    return buf.getvalue()


def decode_text(payload: bytes) -> str:
    buf = io.BytesIO(payload)
    n = read_vlong(buf)
    return buf.read(n).decode("utf-8")


def encode_bytes_writable(data: bytes) -> bytes:
    return struct.pack(">i", len(data)) + data


def decode_bytes_writable(payload: bytes) -> bytes:
    (n,) = struct.unpack_from(">i", payload, 0)
    return payload[4:4 + n]


def encode_int_writable(v: int) -> bytes:
    return struct.pack(">i", v)


def decode_int_writable(payload: bytes) -> int:
    return struct.unpack_from(">i", payload, 0)[0]


# -- file reader / writer --------------------------------------------------

class SequenceFileWriter:
    """Uncompressed record-layout SequenceFile (version 6). A sync marker
    is emitted roughly every ``sync_interval`` bytes like Hadoop's writer,
    so readers (including this module's) exercise the escape path."""

    def __init__(self, path: str, key_class: str = TEXT,
                 value_class: str = BYTES_WRITABLE,
                 sync_interval: int = 2000, seed: int = 0) -> None:
        self._f = open(path, "wb")
        self.key_class = key_class
        self.value_class = value_class
        self._sync_interval = sync_interval
        self._last_sync = 0
        self._sync = np.random.RandomState(seed).bytes(16)
        f = self._f
        f.write(_MAGIC + bytes([6]))
        _write_hadoop_string(f, key_class)
        _write_hadoop_string(f, value_class)
        f.write(b"\x00\x00")                    # compressed, blockCompressed
        f.write(struct.pack(">i", 0))           # metadata entries
        f.write(self._sync)

    def append_raw(self, key: bytes, value: bytes) -> None:
        f = self._f
        if f.tell() - self._last_sync >= self._sync_interval:
            f.write(struct.pack(">i", -1))
            f.write(self._sync)
            self._last_sync = f.tell()
        f.write(struct.pack(">i", len(key) + len(value)))
        f.write(struct.pack(">i", len(key)))
        f.write(key)
        f.write(value)

    def append(self, key, value) -> None:
        """Encode by declared class: Text accepts str, BytesWritable
        bytes, IntWritable/LongWritable int."""
        self.append_raw(_encode_for(self.key_class, key),
                        _encode_for(self.value_class, value))

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _encode_for(cls: str, v) -> bytes:
    if cls == TEXT:
        return encode_text(v)
    if cls == BYTES_WRITABLE:
        return encode_bytes_writable(v)
    if cls == INT_WRITABLE:
        return encode_int_writable(v)
    if cls == LONG_WRITABLE:
        return struct.pack(">q", v)
    raise NotImplementedError(f"no encoder for writable class {cls!r}")


class SequenceFileReader:
    """Iterate ``(key_payload, value_payload)`` raw writable bytes; the
    header's class names are exposed so callers pick decoders."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = f = open(path, "rb")
        magic = f.read(3)
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a SequenceFile (no SEQ magic)")
        self.version = f.read(1)[0]
        if not 4 <= self.version <= 6:
            raise ValueError(
                f"{path}: SequenceFile version {self.version} unsupported "
                "(record layout with leading class names is v4-v6)")
        self.key_class = _read_hadoop_string(f)
        self.value_class = _read_hadoop_string(f)
        compressed = f.read(1)[0] != 0
        # Hadoop's BLOCK_COMPRESS_VERSION is 4, so every supported version
        # (4-6, enforced above) carries the blockCompressed flag byte; only
        # the codec class string (CUSTOM_COMPRESS_VERSION) waits for v5.
        block_compressed = f.read(1)[0] != 0
        codec = None
        if compressed or block_compressed:
            if self.version >= 5:
                codec = _read_hadoop_string(f)
            raise NotImplementedError(
                f"{path}: compressed SequenceFile (codec {codec!r}) — "
                "decompress with Hadoop tooling or repack; this reader "
                "handles the uncompressed record layout")
        if self.version >= 6:
            n_meta = struct.unpack(">i", f.read(4))[0]
            for _ in range(n_meta):
                _read_hadoop_string(f)
                _read_hadoop_string(f)
        self._sync = f.read(16)

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        f = self._f
        while True:
            head = f.read(4)
            if len(head) < 4:
                return
            (rec_len,) = struct.unpack(">i", head)
            if rec_len == -1:                       # sync escape
                marker = f.read(16)
                if marker != self._sync:
                    raise ValueError(
                        f"{self.path}: corrupt sync marker mid-file")
                continue
            (key_len,) = struct.unpack(">i", f.read(4))
            if not 0 <= key_len <= rec_len:
                raise ValueError(
                    f"{self.path}: corrupt record (key {key_len} of "
                    f"{rec_len} bytes)")
            key = f.read(key_len)
            value = f.read(rec_len - key_len)
            if len(key) != key_len or len(value) != rec_len - key_len:
                raise ValueError(f"{self.path}: truncated record")
            yield key, value

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _seq_paths(folder: str) -> List[str]:
    paths = sorted(
        os.path.join(folder, f) for f in os.listdir(folder)
        if f.endswith(".seq") or f.startswith("part-"))
    if not paths:
        raise ValueError(f"no SequenceFiles (*.seq or part-*) under {folder}")
    return paths


def _default_label_of(key: bytes, value: bytes, key_class: str) -> int:
    """The reference packing job wrote the readable label as the Text key
    (possibly 'path<space>label'); IntWritable keys pass through."""
    if key_class == TEXT:
        return int(decode_text(key).split()[-1])
    if key_class == INT_WRITABLE:
        return decode_int_writable(key)
    if key_class == LONG_WRITABLE:
        return struct.unpack(">q", key[:8])[0]
    raise NotImplementedError(
        f"cannot derive a label from key class {key_class!r} — pass "
        "label_of=")


def convert_to_recs(src_folder: str, out_dir: str, n_shards: int = 8,
                    label_of: Optional[Callable] = None,
                    payload_of: Optional[Callable] = None) -> List[str]:
    """Repack a SequenceFile folder into RECS shards (the native format
    the C++ indexer and the measured host pipeline consume). Default
    mapping is the reference ImageNet convention: label from the Text/Int
    key, payload from the BytesWritable value."""
    from bigdl_tpu.dataset.seqfile import write_shards

    def records() -> Iterator[Tuple[int, bytes]]:
        for path in _seq_paths(src_folder):
            with SequenceFileReader(path) as r:
                for key, value in r:
                    if label_of is not None:
                        label = label_of(key, value)
                    else:
                        label = _default_label_of(key, value, r.key_class)
                    if payload_of is not None:
                        payload = payload_of(key, value)
                    elif r.value_class == BYTES_WRITABLE:
                        payload = decode_bytes_writable(value)
                    else:
                        payload = value
                    yield int(label), payload

    return write_shards(list(records()), out_dir, n_shards=n_shards)


def _np_label(label: int) -> np.ndarray:
    """int64 when the value needs it (LongWritable keys can exceed int32 —
    the RECS side preserves those too), int32 otherwise."""
    label = int(label)
    if not -2 ** 31 <= label < 2 ** 31:
        return np.int64(label)
    return np.int32(label)


class HadoopSeqFileDataSet(LocalDataSet):
    """Direct streaming ingestion of a SequenceFile folder with the same
    shard-per-process round-robin AND the same dataset contract as
    ``SeqFileDataSet`` (``Optimizer``-consumable, ``ds >> transformer``
    chains). For repeated epochs over big corpora prefer
    :func:`convert_to_recs` once — RECS rides the native indexer; this
    class re-parses Java framing every epoch.

    ``decoder(label, payload)`` has the SAME signature as the RECS
    dataset's (label from the Text/Int/Long key, payload unwrapped from
    BytesWritable) so one decoder serves both formats across a
    ``convert_to_recs`` migration; pass ``label_of(key_bytes,
    value_bytes)`` for exotic key schemes. Raw key/value access =
    :class:`SequenceFileReader` directly."""

    def __init__(self, folder: str,
                 decoder: Optional[Callable] = None,
                 shard_index: int = 0, num_shards: int = 1,
                 seed: int = 0, transformers=None,
                 label_of: Optional[Callable] = None) -> None:
        self._folder = folder
        all_paths = _seq_paths(folder)
        self.paths = all_paths[shard_index::num_shards]
        if not self.paths:
            raise ValueError(
                f"process {shard_index}/{num_shards} gets no files — "
                f"{folder} holds only {len(all_paths)}")
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.decoder = decoder
        self.label_of = label_of
        self._seed = seed
        self._transformers = list(transformers or [])
        self._epoch = 0
        self._size: Optional[int] = None

    def _decode(self, reader, key, value):
        label = (self.label_of(key, value) if self.label_of is not None
                 else _default_label_of(key, value, reader.key_class))
        payload = (decode_bytes_writable(value)
                   if reader.value_class == BYTES_WRITABLE else value)
        if self.decoder is not None:
            return self.decoder(int(label), payload)
        from bigdl_tpu.dataset.sample import Sample

        return Sample(np.frombuffer(payload, np.uint8).copy(),
                      _np_label(label))

    def size(self) -> int:
        if self._size is None:
            n = 0
            for p in self.paths:
                with SequenceFileReader(p) as r:
                    for _ in r:
                        n += 1
            self._size = n
        return self._size

    def transform(self, transformer) -> "HadoopSeqFileDataSet":
        return HadoopSeqFileDataSet(
            self._folder, self.decoder, self.shard_index, self.num_shards,
            self._seed, self._transformers + [transformer], self.label_of)

    __rshift__ = transform

    def _iter_once(self, shuffle: bool):
        rng = np.random.default_rng(self._seed + self._epoch)
        order = list(self.paths)
        if shuffle:
            rng.shuffle(order)
        for path in order:
            with SequenceFileReader(path) as r:
                records = list(r)
                if shuffle:
                    rng.shuffle(records)
                for key, value in records:
                    yield self._decode(r, key, value)

    def _base_iter(self, train: bool):
        if not train:
            yield from self._iter_once(shuffle=False)
            return
        while True:
            yield from self._iter_once(shuffle=True)
            self._epoch += 1
