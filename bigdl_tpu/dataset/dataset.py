"""DataSet — local and distributed dataset abstractions.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dataset/DataSet.scala`` —
``DataSet.array`` (local), ``DataSet.rdd`` (distributed),
``LocalDataSet``/``DistributedDataSet`` exposing ``data(train=)`` iterators
(infinite shuffled for train, one-pass for eval) and ``size()``; the
``Optimizer`` factory dispatches Local vs Distri on the dataset type.

TPU-native redesign: there is no RDD — a *distributed* dataset means "this
process loads its 1/process_count shard and batches are laid out for the
device mesh". ``DataSet.array(...)`` → ``LocalDataSet``;
``DataSet.rdd(...)`` / ``.distributed()`` → ``DistributedDataSet`` (same
host-side iterator machinery, plus shard arithmetic). Feeding 256 chips is
the real bottleneck at pod scale (SURVEY.md §7), so the iterator layer stays
thin numpy and the optimizer overlaps host→device transfer with compute.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer


class AbstractDataSet:
    def data(self, train: bool) -> Iterator[Any]:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        pass

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        raise NotImplementedError

    __rshift__ = None  # set below


class LocalDataSet(AbstractDataSet):
    def __init__(self, data: Sequence[Any], transformers: Optional[List[Transformer]] = None,
                 seed: int = 0) -> None:
        self._data = list(data)
        self._transformers = transformers or []
        self._seed = seed

    def size(self) -> int:
        return len(self._data)

    def transform(self, transformer: Transformer) -> "LocalDataSet":
        out = type(self)(self._data, self._transformers + [transformer], self._seed)
        return out

    __rshift__ = transform  # dataset >> transformer, mirroring `->`

    def _base_iter(self, train: bool) -> Iterator[Any]:
        if train:
            rng = np.random.RandomState(self._seed)
            n = len(self._data)
            while True:
                order = rng.permutation(n)
                for i in order:
                    yield self._data[i]
        else:
            yield from self._data

    def data(self, train: bool) -> Iterator[Any]:
        it: Iterator[Any] = self._base_iter(train)
        for t in self._transformers:
            it = t(it)
        return it


class DistributedDataSet(LocalDataSet):
    """Shard-aware dataset: holds this process's shard of the global data.

    ``partition_num`` mirrors the reference's RDD partition count; in SPMD
    terms it is the number of processes. The Optimizer factory returns a
    DistriOptimizer for this type (reference ``object Optimizer.apply``).
    """

    def __init__(self, data: Sequence[Any], transformers=None, seed: int = 0,
                 partition_num: int = 1, partition_index: int = 0) -> None:
        super().__init__(data, transformers, seed)
        self.partition_num = partition_num
        self.partition_index = partition_index

    def transform(self, transformer: Transformer) -> "DistributedDataSet":
        return DistributedDataSet(
            self._data, self._transformers + [transformer], self._seed,
            self.partition_num, self.partition_index,
        )

    __rshift__ = transform


class _DataSetFactory:
    """``DataSet.array`` / ``DataSet.rdd`` factories (reference ``object DataSet``)."""

    @staticmethod
    def array(data: Sequence[Any], seed: int = 0) -> LocalDataSet:
        return LocalDataSet(data, seed=seed)

    @staticmethod
    def distributed(data: Sequence[Any], seed: int = 0) -> DistributedDataSet:
        """Global data → this process's shard (multi-host SPMD)."""
        import jax

        n_proc = jax.process_count()
        idx = jax.process_index()
        shard = list(data)[idx::n_proc]
        return DistributedDataSet(
            shard, seed=seed, partition_num=n_proc, partition_index=idx
        )

    # reference name: DataSet.rdd(...)
    rdd = distributed

    @staticmethod
    def image_folder(path: str, **kwargs):
        from bigdl_tpu.dataset.image import image_folder_samples

        return _DataSetFactory.array(image_folder_samples(path, **kwargs))

    @staticmethod
    def seq_file_folder(path: str, decoder=None, seed: int = 0,
                        format: str = "recs"):
        """Sharded record-file ingestion (reference ``DataSet.SeqFileFolder``
        — ImageNet-as-SequenceFiles). Shards are split across processes.
        ``format="hadoop"`` streams actual Hadoop SequenceFiles (a
        reference user's existing corpus) via
        ``dataset/hadoop_seqfile.py``; the default reads this framework's
        RECS shards (convert once with ``hadoop_seqfile.convert_to_recs``
        for the native-indexer fast path). ``decoder(label, payload)``
        has the SAME signature for both formats (hadoop derives the label
        from the Text/Int/Long key and unwraps BytesWritable first), so
        one decoder survives a convert_to_recs migration."""
        import jax

        if format not in ("recs", "hadoop"):
            raise ValueError(
                f"unknown seq_file_folder format {format!r} — expected "
                "'recs' (native shards) or 'hadoop' (SequenceFiles)")
        if format == "hadoop":
            from bigdl_tpu.dataset.hadoop_seqfile import HadoopSeqFileDataSet

            return HadoopSeqFileDataSet(
                path, decoder=decoder, seed=seed,
                shard_index=jax.process_index(),
                num_shards=jax.process_count(),
            )
        from bigdl_tpu.dataset.seqfile import SeqFileDataSet

        return SeqFileDataSet(
            path, decoder=decoder, seed=seed,
            shard_index=jax.process_index(), num_shards=jax.process_count(),
        )


DataSet = _DataSetFactory()
