"""Text pipeline — dictionary, sentence transformers, padding/bucketing.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dataset/text/`` —
``Dictionary.scala``, ``TextToLabeledSentence.scala``,
``LabeledSentenceToSample.scala``, ``SentenceTokenizer``, padding
transformers; used by the rnn PTB language model and the textclassifier
target configs (SURVEY.md §2.5, §2.8).

TPU-native notes: text prep is host-side (CPU) work that feeds fixed-shape
integer batches to the device; everything here produces STATIC shapes
(pad/truncate to ``sequence_len``) so one XLA program serves every batch.
"""

from __future__ import annotations

import collections
import re
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

SENTENCE_START = "SENTENCE_START"
SENTENCE_END = "SENTENCE_END"


def simple_tokenize(text: str) -> List[str]:
    """Lowercase word tokenizer (reference ``SentenceTokenizer`` role)."""
    return re.findall(r"[a-z0-9']+", text.lower())


class Dictionary:
    """Word-frequency vocabulary (reference ``text/Dictionary.scala``):
    keeps the ``vocab_size`` most frequent words; everything else maps to one
    out-of-vocabulary index (the last index)."""

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None) -> None:
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = collections.Counter(
                w for sent in sentences for w in sent
            )
            keep = (counts.most_common(vocab_size)
                    if vocab_size is not None else sorted(counts.items()))
            for w, _ in keep:
                self.add_word(w)

    def add_word(self, word: str) -> int:
        if word not in self.word2index:
            self.word2index[word] = len(self.index2word)
            self.index2word.append(word)
        return self.word2index[word]

    def vocab_size(self) -> int:
        """Vocabulary size INCLUDING the out-of-vocab slot."""
        return len(self.index2word) + 1

    def get_index(self, word: str) -> int:
        """In-vocab index, or the OOV index (vocab_size - 1)."""
        return self.word2index.get(word, len(self.index2word))

    def get_word(self, index: int) -> str:
        if 0 <= index < len(self.index2word):
            return self.index2word[index]
        return "<unk>"

    def __len__(self) -> int:
        return self.vocab_size()


class LabeledSentence:
    """An indexed sentence with per-position labels (reference
    ``text/LabeledSentence.scala``): for language modelling the label is the
    next word; for classification a single class id."""

    def __init__(self, data: Sequence[int], labels: Sequence[int]) -> None:
        self.data = list(data)
        self.labels = list(labels)

    def data_length(self) -> int:
        return len(self.data)

    def label_length(self) -> int:
        return len(self.labels)


class TextToLabeledSentence(Transformer):
    """token sequences → next-word-prediction ``LabeledSentence``s
    (reference ``text/TextToLabeledSentence.scala``): wraps each sentence
    with start/end markers and labels every position with the next word."""

    def __init__(self, dictionary: Dictionary) -> None:
        self.dictionary = dictionary
        # the markers can never come out of a tokenizer — register them so
        # sentence boundaries don't silently collapse onto the OOV index
        self.start_idx = dictionary.add_word(SENTENCE_START)
        self.end_idx = dictionary.add_word(SENTENCE_END)

    def apply(self, it: Iterator[Sequence[str]]) -> Iterator[LabeledSentence]:
        for tokens in it:
            idx = [self.start_idx] + [self.dictionary.get_index(t) for t in tokens] \
                + [self.end_idx]
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """``LabeledSentence`` → fixed-length ``Sample`` (reference
    ``text/LabeledSentenceToSample.scala``): pads/truncates to
    ``sequence_len``.

    Non-one-hot features are 1-based word ids for a ``LookupTable`` front
    (id 0 = padding, which LookupTable embeds to the zero vector); one-hot
    mode expands 0-based rows. Labels are 1-based (ClassNLL convention),
    padded with class 1."""

    def __init__(self, vocab_size: int, sequence_len: int,
                 one_hot: bool = False) -> None:
        self.vocab_size = vocab_size
        self.sequence_len = sequence_len
        self.one_hot = one_hot

    def apply(self, it: Iterator[LabeledSentence]) -> Iterator[Sample]:
        L = self.sequence_len
        for s in it:
            n = min(L, len(s.data))
            if self.one_hot:
                feat = np.zeros((L, self.vocab_size), np.float32)
                feat[np.arange(n), np.asarray(s.data[:n], np.int64)] = 1.0
            else:
                feat = np.zeros((L,), np.float32)
                feat[:n] = np.asarray(s.data[:n], np.float32) + 1.0
            labels = np.ones((L,), np.float32)
            labels[:n] = np.asarray(s.labels[:n], np.float32) + 1.0
            yield Sample(feat, labels)


class SequenceWindower(Transformer):
    """Long token-id streams → contiguous next-word windows for language
    modelling (the reference PTB pipeline's fixed ``numSteps`` batching):
    yields ``LabeledSentence(ids[i:i+L], ids[i+1:i+L+1])`` with stride ``L``;
    the ragged tail is dropped, so no padding ever enters the LM loss."""

    def __init__(self, sequence_len: int) -> None:
        self.sequence_len = sequence_len

    def apply(self, it: Iterator[Sequence[int]]) -> Iterator[LabeledSentence]:
        L = self.sequence_len
        for ids in it:
            for i in range(0, len(ids) - L, L):
                yield LabeledSentence(ids[i:i + L], ids[i + 1:i + L + 1])


class SentenceToWordIndices(Transformer):
    """(tokens, label) pairs → classification ``Sample``s: pad/truncate the
    token ids to ``sequence_len``; label passes through unchanged (the
    textclassifier pipeline's shape)."""

    def __init__(self, dictionary: Dictionary, sequence_len: int,
                 pad_index: int = 0) -> None:
        self.dictionary = dictionary
        self.sequence_len = sequence_len
        self.pad_index = pad_index

    def apply(self, it: Iterator[Tuple[Sequence[str], Any]]) -> Iterator[Sample]:
        L = self.sequence_len
        for tokens, label in it:
            idx = [self.dictionary.get_index(t) + 1 for t in tokens][:L]
            idx = idx + [self.pad_index] * (L - len(idx))
            yield Sample(np.asarray(idx, np.float32), np.float32(label))
