"""CIFAR-10 loader (reference VGG config's dataset).

Reads the python-pickle batch format from disk when present; synthetic
fallback otherwise (zero-egress sandbox).
"""

from __future__ import annotations

import os
import pickle
from typing import List, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN = (125.3, 123.0, 113.9)
TRAIN_STD = (63.0, 62.1, 66.7)


def _synthetic(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    protos = np.random.RandomState(99).rand(10, 3, 32, 32).astype(np.float32) * 255
    labels = rng.randint(0, 10, n)
    imgs = 0.6 * protos[labels] + 0.4 * rng.rand(n, 3, 32, 32).astype(np.float32) * 255
    return imgs.astype(np.uint8), labels.astype(np.uint8)


def read_data_sets(data_dir: str, kind: str = "train",
                   synthetic_fallback: bool = True,
                   synthetic_count: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """(images uint8 (N,3,32,32), labels uint8 0-9)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    root = batch_dir if os.path.isdir(batch_dir) else data_dir
    names = (
        [f"data_batch_{i}" for i in range(1, 6)] if kind == "train" else ["test_batch"]
    )
    imgs, labels = [], []
    for name in names:
        p = os.path.join(root, name)
        if not os.path.exists(p):
            if synthetic_fallback:
                seed = 21 if kind == "train" else 22
                return _synthetic(synthetic_count, seed)
            raise FileNotFoundError(p)
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32))
        labels.append(np.asarray(d[b"labels"], np.uint8))
    return np.concatenate(imgs), np.concatenate(labels)


def load_samples(data_dir: str, kind: str = "train", **kw) -> List[Sample]:
    imgs, labels = read_data_sets(data_dir, kind, **kw)
    return [
        Sample(imgs[i].astype(np.float32), np.float32(labels[i] + 1))
        for i in range(len(imgs))
    ]
