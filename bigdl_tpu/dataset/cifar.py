"""CIFAR-10 loader (reference VGG config's dataset).

Reads the python-pickle batch format from disk when present; synthetic
fallback otherwise (zero-egress sandbox).
"""

from __future__ import annotations

import os
import pickle
from typing import List, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN = (125.3, 123.0, 113.9)
TRAIN_STD = (63.0, 62.1, 66.7)


def _synthetic(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    protos = np.random.RandomState(99).rand(10, 3, 32, 32).astype(np.float32) * 255
    labels = rng.randint(0, 10, n)
    imgs = 0.6 * protos[labels] + 0.4 * rng.rand(n, 3, 32, 32).astype(np.float32) * 255
    return imgs.astype(np.uint8), labels.astype(np.uint8)


def _synthetic_learnable(n: int, seed: int,
                         noise: float) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable 10-class CIFAR-shaped set: smooth per-class
    prototype fields + pixel noise. ``noise`` tunes difficulty so
    convergence tests land below the saturation ceiling (a model at 100%
    makes cross-framework parity vacuous)."""
    rng = np.random.RandomState(seed)
    proto_rng = np.random.RandomState(1234)
    base = proto_rng.rand(10, 3, 8, 8).astype(np.float32)
    protos = np.repeat(np.repeat(base, 4, axis=2), 4, axis=3) * 255.0
    labels = (np.arange(n) % 10).astype(np.uint8)
    perm = rng.permutation(n)
    labels = labels[perm]
    imgs = protos[labels] + rng.randn(n, 3, 32, 32).astype(np.float32) * noise
    return np.clip(imgs, 0, 255).astype(np.uint8), labels


def generate_batch_dataset(data_dir: str, n_train: int = 2048,
                           n_test: int = 1024, seed: int = 0,
                           noise: float = 64.0) -> None:
    """Write a learnable synthetic set as REAL CIFAR pickle batch files
    (``data_batch_1..5`` + ``test_batch``), so convergence tests exercise
    the real reader path end to end (mirror of
    ``mnist.generate_idx_dataset``)."""
    os.makedirs(data_dir, exist_ok=True)
    imgs, labels = _synthetic_learnable(n_train, seed, noise)
    per = -(-n_train // 5)
    for i in range(5):
        lo, hi = i * per, min((i + 1) * per, n_train)
        with open(os.path.join(data_dir, f"data_batch_{i + 1}"), "wb") as f:
            pickle.dump({b"data": imgs[lo:hi].reshape(hi - lo, -1),
                         b"labels": labels[lo:hi].tolist()}, f)
    imgs_t, labels_t = _synthetic_learnable(n_test, seed + 1, noise)
    with open(os.path.join(data_dir, "test_batch"), "wb") as f:
        pickle.dump({b"data": imgs_t.reshape(n_test, -1),
                     b"labels": labels_t.tolist()}, f)


def read_data_sets(data_dir: str, kind: str = "train",
                   synthetic_fallback: bool = True,
                   synthetic_count: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """(images uint8 (N,3,32,32), labels uint8 0-9)."""
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    root = batch_dir if os.path.isdir(batch_dir) else data_dir
    names = (
        [f"data_batch_{i}" for i in range(1, 6)] if kind == "train" else ["test_batch"]
    )
    imgs, labels = [], []
    for name in names:
        p = os.path.join(root, name)
        if not os.path.exists(p):
            if synthetic_fallback:
                seed = 21 if kind == "train" else 22
                return _synthetic(synthetic_count, seed)
            raise FileNotFoundError(p)
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32))
        labels.append(np.asarray(d[b"labels"], np.uint8))
    return np.concatenate(imgs), np.concatenate(labels)


def load_samples(data_dir: str, kind: str = "train", **kw) -> List[Sample]:
    imgs, labels = read_data_sets(data_dir, kind, **kw)
    return [
        Sample(imgs[i].astype(np.float32), np.float32(labels[i] + 1))
        for i in range(len(imgs))
    ]
