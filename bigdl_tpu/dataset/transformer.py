"""Transformer — composable iterator→iterator stages.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/dataset/Transformer.scala``
— a serializable ``Iterator[A] => Iterator[B]`` composed with ``->`` and
cloned per partition.

Python surface: compose with ``>>`` (or ``.and_then``); a transformer is a
callable over an iterator. ``SampleToMiniBatch`` is the batching stage.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from bigdl_tpu.dataset.sample import MiniBatch, Sample, stack_samples


class Transformer:
    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        raise NotImplementedError

    def __call__(self, it: Iterable[Any]) -> Iterator[Any]:
        return self.apply(iter(it))

    def and_then(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    __rshift__ = and_then  # `a >> b` mirrors the reference's `a -> b`


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, second: Transformer) -> None:
        self.first = first
        self.second = second

    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        return self.second(self.first(it))


class FnTransformer(Transformer):
    """Lift a per-record function into a Transformer."""

    def __init__(self, fn: Callable[[Any], Any]) -> None:
        self.fn = fn

    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        for x in it:
            yield self.fn(x)


class Identity(Transformer):
    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        return it


class SampleToMiniBatch(Transformer):
    """Group a sample stream into MiniBatches of ``batch_size``
    (reference ``SampleToMiniBatch.scala``). Drops the trailing partial
    batch when ``drop_remainder`` (static shapes keep XLA from recompiling —
    the TPU analog of the reference's fixed per-core batch)."""

    def __init__(self, batch_size: int, drop_remainder: bool = True) -> None:
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def apply(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield stack_samples(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield stack_samples(buf)


SampleToBatch = SampleToMiniBatch  # early-reference alias
