"""TPU fusion pass — an alternate NHWC lowering of a ``Graph`` model.

Reference precedent (UNVERIFIED, SURVEY.md §0): the mkldnn engine —
``.../bigdl/nn/mkldnn/*`` is a parallel layer world the engine selects for
``EngineType.MklDnn``, with its own blocked layouts and conv+ReLU/BN/sum
fusion (``SpatialConvolution.setReLU/setSum``). ``FusedGraph`` is the
TPU-engine analog: SAME params/state pytrees as the wrapped ``Graph``
(checkpoints, serializer and optimizer state interop unchanged), different
execution.

What it does:

* Executes the DAG **channels-last** (NHWC): XLA:TPU conv performance is
  layout-neutral (benchmarks/layout_experiment.py), but channels-last makes
  a 1×1 conv a plain (N·H·W, C)×(C, K) matmul over contiguous rows — the
  shape the Pallas fused kernels need. Modules without an NHWC adapter run
  via transpose→module.apply→transpose fallback (correct for any graph,
  fast for none — the adapter table covers the ResNet/VGG family).
* Pattern-matches **BN→ReLU→1×1 conv** edges (optionally through the
  residual ``CAddTable``) and lowers each to one
  :func:`bigdl_tpu.ops.fused_conv.bn_relu_conv1x1` call — the activation
  between BN and conv is never materialized in HBM (PERF_ANALYSIS_r2.md:
  the ``maximum_add_fusion`` passes XLA cannot prologue-fuse).
* Threads the kernels' per-channel ``Σz/Σz²`` epilogue stats into the next
  BN (fused or not), so no separate stats pass re-reads a Pallas output.
* Preserves BN running-stat semantics exactly (biased batch var for
  normalize, unbiased in the running buffer, ``r = (1−m)r + m·batch``).

Use :func:`maybe_fuse` to wrap a model when the engine enables conv fusion.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu.nn.activations import ReLU
from bigdl_tpu.nn.conv import SpatialConvolution
from bigdl_tpu.nn.containers import Sequential
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.module import AbstractModule, Identity
from bigdl_tpu.nn.normalization import SpatialBatchNormalization
from bigdl_tpu.nn.pooling import SpatialAveragePooling, SpatialMaxPooling
from bigdl_tpu.nn.shape_ops import CAddTable


def _pallas_min_c() -> int:
    """Per-edge lowering threshold. Isolated 2-edge chains favor the
    Pallas kernel at C ≥ 128, but in a full model every custom-call
    boundary forces XLA to relayout operands to the default layout
    (PERF_ANALYSIS_r3.md: +20 ms/step of copies), so the default keeps
    every edge on the XLA dot. Env override: BIGDL_PALLAS_MIN_C=128
    re-enables the kernels for layout-clean workloads/experiments."""
    import os

    return int(os.environ.get("BIGDL_PALLAS_MIN_C", str(1 << 30)))


class _PNode:
    """Primitive node of the expanded DAG: a leaf module + its params path
    (graph key, then container child keys) + predecessor _PNodes."""

    __slots__ = ("module", "path", "preds", "is_input")

    def __init__(self, module, path, preds, is_input=False):
        self.module = module
        self.path = path
        self.preds: List[_PNode] = preds
        self.is_input = is_input


def _expand(graph: Graph):
    """Graph topo → primitive DAG (Sequentials flattened, params paths
    recorded). Non-Sequential containers stay opaque primitives."""
    node_out: Dict[int, _PNode] = {}
    pnodes: List[_PNode] = []

    def expand_module(module, path, preds):
        if isinstance(module, Sequential) and len(module.modules) > 0:
            cur = preds
            last = None
            for i, child in enumerate(module.modules):
                last = expand_module(child, path + (module._child_key(i),),
                                     cur)
                cur = [last]
            return last
        p = _PNode(module, path, preds)
        pnodes.append(p)
        return p

    input_pn = {}
    for node in graph.topo:
        nid = id(node)
        if node in graph.input_nodes:
            p = _PNode(node.module, (), [], is_input=True)
            pnodes.append(p)
            node_out[nid] = p
            input_pn[nid] = p
            continue
        preds = [node_out[id(q)] for q in node.prev]
        key = graph._module_keys[id(node.module)]
        node_out[nid] = expand_module(node.module, (key,), preds)
    outs = [node_out[id(n)] for n in graph.output_nodes]
    ins = [input_pn[id(n)] for n in graph.input_nodes]
    return pnodes, ins, outs


def _is_fusable_conv(m) -> bool:
    return (isinstance(m, SpatialConvolution)
            and m.kernel_w == 1 and m.kernel_h == 1
            and m.stride_w == 1 and m.stride_h == 1
            and m.pad_w == 0 and m.pad_h == 0
            and m.n_group == 1 and not m.with_bias)


class _FusedEdge:
    """One lowered BN→ReLU→conv1×1 edge (optionally through CAddTable)."""

    __slots__ = ("bn", "relu", "conv", "add", "residual_src", "want_y")

    def __init__(self, bn, relu, conv, add=None, residual_src=None,
                 want_y=False):
        self.bn = bn
        self.relu = relu
        self.conv = conv
        self.add = add
        self.residual_src = residual_src
        self.want_y = want_y


def _tree_get(tree, path):
    for k in path:
        tree = tree.get(k, {}) if isinstance(tree, dict) else {}
    return tree


def _tree_set(tree, path, value):
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = value


class FusedGraph(AbstractModule):
    """Drop-in wrapper: same params/state pytrees as ``graph``, NHWC fused
    execution. Falls back per-module (with transposes) for anything the
    adapter table doesn't cover, so output parity holds for any graph."""

    def __init__(self, graph: Graph) -> None:
        super().__init__()
        self.graph = graph
        self.name = graph.name
        self._build_plan()

    # -- params/state interop: pure delegation -------------------------
    def init_params(self, rng):
        return self.graph.init_params(rng)

    def init_state(self):
        return self.graph.init_state()

    def sub_modules(self):
        return self.graph.sub_modules()

    # -- plan ----------------------------------------------------------
    def _build_plan(self) -> None:
        pnodes, ins, outs = _expand(self.graph)
        self._pnodes, self._ins, self._outs = pnodes, ins, outs
        consumers: Dict[int, int] = {}
        for p in pnodes:
            for q in p.preds:
                consumers[id(q)] = consumers.get(id(q), 0) + 1
        for o in outs:
            consumers[id(o)] = consumers.get(id(o), 0) + 1

        order = {id(p): i for i, p in enumerate(pnodes)}
        consumed: Dict[int, _FusedEdge] = {}  # nid -> owning edge
        edges: Dict[int, _FusedEdge] = {}     # conv nid -> edge

        for conv in pnodes:
            if not _is_fusable_conv(conv.module) or len(conv.preds) != 1:
                continue
            relu = conv.preds[0]
            if not isinstance(relu.module, ReLU) or id(relu) in consumed:
                continue
            if len(relu.preds) != 1:
                continue
            src = relu.preds[0]
            want_y = consumers.get(id(relu), 0) > 1 or relu in outs
            if want_y:
                # y's other consumers must run after the conv produces it
                later = all(order[id(p)] > order[id(conv)]
                            for p in pnodes
                            if any(q is relu for q in p.preds)
                            and p is not conv)
                if not later:
                    continue
            bn = add = residual = None
            if isinstance(src.module, SpatialBatchNormalization):
                if consumers.get(id(src), 0) != 1 or len(src.preds) != 1:
                    continue
                bn = src
            elif isinstance(src.module, CAddTable) and len(src.preds) == 2:
                if consumers.get(id(src), 0) != 1:
                    continue
                cand = src.preds[0]
                if (isinstance(cand.module, SpatialBatchNormalization)
                        and consumers.get(id(cand), 0) == 1
                        and len(cand.preds) == 1
                        and id(cand) not in consumed):
                    bn, add, residual = cand, src, src.preds[1]
                else:
                    continue
            else:
                continue
            if id(bn) in consumed or id(relu) in consumed:
                continue
            edge = _FusedEdge(bn, relu, conv, add=add,
                              residual_src=residual, want_y=want_y)
            edges[id(conv)] = edge
            consumed[id(bn)] = edge
            consumed[id(relu)] = edge
            if add is not None:
                consumed[id(add)] = edge
        self._edges = edges
        self._consumed = consumed

    # -- execution ------------------------------------------------------
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.ops.fused_conv import bn_relu_conv1x1

        state = state or {}
        new_state = jax.tree_util.tree_map(lambda x: x, state)  # deep-ish copy
        if not isinstance(new_state, dict):
            new_state = dict(state)

        def pstate(p):
            return _tree_get(state, p.path)

        def set_state(p, s):
            _tree_set(new_state, p.path, s)

        def pparams(p):
            return _tree_get(params, p.path)

        values: Dict[int, Any] = {}
        stats: Dict[int, Any] = {}  # nid -> (2, C) f32 epilogue stats

        inputs = input if isinstance(input, (list, tuple)) else [input]
        for pn, v in zip(self._ins, inputs):
            if v.ndim == 4:  # NCHW boundary -> NHWC internal
                v = jnp.transpose(v, (0, 2, 3, 1))
            values[id(pn)] = v

        def batch_stats(x_nhwc, nid, use_cache):
            """(mean, var) per channel. A fused producer's epilogue stats
            (``use_cache``) are stop-gradient'd — ONLY the Pallas edge's
            custom VJP may consume them, because it re-derives the
            stats-backward terms itself. Every other consumer needs the
            differentiable jnp reduction (standard autodiff owns the
            correction), which XLA fuses into an XLA producer's epilogue."""
            m = x_nhwc.size // x_nhwc.shape[-1]
            if use_cache and nid in stats:
                st = stats[nid]
                mean = st[0] / m
                var = jnp.maximum(st[1] / m - mean * mean, 0.0)
                return mean, var, m
            xf = x_nhwc.astype(jnp.float32)
            axes = tuple(range(x_nhwc.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean,
                              0.0)
            return mean, var, m

        def bn_mv(bnode, x_val, src_nid, use_cache=False):
            """mean/var for this BN + running-stats update (exact
            BatchNormalization semantics: biased var to normalize, unbiased
            in the buffer)."""
            bnmod = bnode.module
            st = pstate(bnode)
            if training:
                mean, var, n = batch_stats(x_val, src_nid, use_cache)
                unbiased = var * (n / max(n - 1, 1))
                mom = bnmod.momentum
                set_state(bnode, {
                    "running_mean": (1 - mom) * st["running_mean"]
                    + mom * mean,
                    "running_var": (1 - mom) * st["running_var"]
                    + mom * unbiased,
                })
            else:
                mean, var = st["running_mean"], st["running_var"]
                set_state(bnode, st)
            return mean, var

        def run_fused(edge):
            bnode = edge.bn
            src_nid = id(bnode.preds[0])
            x_val = values[src_nid]
            n, h, w_, c = x_val.shape
            use_pallas = c >= _pallas_min_c()
            mean, var = bn_mv(bnode, x_val, src_nid, use_cache=use_pallas)
            bn_p = pparams(bnode)
            gamma = bn_p.get("weight", jnp.ones((c,), jnp.float32))
            beta = bn_p.get("bias", jnp.zeros((c,), jnp.float32))
            w4 = pparams(edge.conv)["weight"]          # OIHW (K, C, 1, 1)
            w2 = w4[:, :, 0, 0].T                      # (C, K)
            k = w2.shape[1]
            # per-edge lowering (measured, benchmarks/fused_conv_experiment
            # + PERF_ANALYSIS_r3.md): the Pallas kernel wins isolated
            # chains at C >= 128, but in-model its custom-call boundaries
            # force layout copies — the default threshold keeps every edge
            # on the XLA dot (override: BIGDL_PALLAS_MIN_C).
            if use_pallas:
                # (N,H,W,C) -> (N·H, W, C) is a FREE view of the tiled
                # layout; a 2-D flatten would physically repack HBM
                residual = None
                if edge.residual_src is not None:
                    residual = values[id(edge.residual_src)] \
                        .reshape(n * h, w_, c)
                out = bn_relu_conv1x1(
                    x_val.reshape(n * h, w_, c), gamma, beta,
                    jax.lax.stop_gradient(mean.astype(jnp.float32)),
                    jax.lax.stop_gradient(var.astype(jnp.float32)),
                    w2, residual, bnode.module.eps, edge.want_y)
                stats[id(edge.conv)] = out[1]
                values[id(edge.conv)] = out[0].reshape(n, h, w_, k)
                if edge.want_y:
                    values[id(edge.relu)] = out[2].reshape(n, h, w_, c)
            else:
                # 4-D end to end (a reshape of a TPU-tiled NHWC array is a
                # physical repack), elementwise in the INPUT dtype
                # (module-BN discipline: f32 intermediates double the HBM
                # bytes of saved residuals and backward cotangents)
                inv = (1.0 / jnp.sqrt(var + bnode.module.eps))
                scale = (inv * gamma).astype(x_val.dtype)
                shift = (beta - mean * inv * gamma).astype(x_val.dtype)
                p = x_val * scale + shift
                if edge.residual_src is not None:
                    p = p + values[id(edge.residual_src)]
                y4 = jnp.maximum(p, 0.0)
                z4 = jax.lax.dot_general(
                    y4, w2.astype(y4.dtype),
                    dimension_numbers=(((3,), (0,)), ((), ())))
                values[id(edge.conv)] = z4
                if edge.want_y:
                    values[id(edge.relu)] = y4
            set_state(edge.relu, {})
            set_state(edge.conv, {})
            if edge.add is not None:
                set_state(edge.add, {})

        def run_prim(p, child_rng):
            args = [values[id(q)] for q in p.preds]
            x = args[0] if len(args) == 1 else args
            m = p.module
            if isinstance(m, SpatialConvolution) and x.ndim == 4 \
                    and m.n_group == 1:
                values[id(p)] = _conv_nhwc(m, pparams(p), x)
                set_state(p, pstate(p))
            elif isinstance(m, SpatialBatchNormalization) and x.ndim == 4:
                mean, var = bn_mv(p, x, id(p.preds[0]))
                bn_p = pparams(p)
                inv = (1.0 / jnp.sqrt(var + m.eps)).astype(x.dtype)
                out = (x - mean.astype(x.dtype)) * inv
                if m.affine:
                    out = out * bn_p["weight"].astype(x.dtype) \
                        + bn_p["bias"].astype(x.dtype)
                values[id(p)] = out
            elif isinstance(m, (SpatialMaxPooling, SpatialAveragePooling)) \
                    and x.ndim == 4:
                values[id(p)] = _pool_nhwc(m, x)
                set_state(p, pstate(p))
            elif isinstance(m, (ReLU, CAddTable, Identity)) or \
                    type(m).__name__ in _AGNOSTIC:
                out, st = m.apply(pparams(p), x, pstate(p),
                                  training=training, rng=child_rng)
                values[id(p)] = out
                set_state(p, st)
            else:
                # correct-for-anything fallback: hand the module NCHW
                def to_nchw(v):
                    return jnp.transpose(v, (0, 3, 1, 2)) \
                        if hasattr(v, "ndim") and v.ndim == 4 else v

                def to_nhwc(v):
                    return jnp.transpose(v, (0, 2, 3, 1)) \
                        if hasattr(v, "ndim") and v.ndim == 4 else v

                xin = [to_nchw(v) for v in args]
                xin = xin[0] if len(xin) == 1 else xin
                out, st = m.apply(pparams(p), xin, pstate(p),
                                  training=training, rng=child_rng)
                values[id(p)] = to_nhwc(out)
                set_state(p, st)

        for i, p in enumerate(self._pnodes):
            if p.is_input:
                continue
            if id(p) in self._edges:
                run_fused(self._edges[id(p)])
                continue
            if id(p) in self._consumed:
                continue  # produced by its owning fused edge
            # thread rng like Graph.apply does (Dropout et al. are
            # identity under rng=None — dropping it would silently
            # disable them in training)
            child_rng = None if rng is None else jax.random.fold_in(rng, i)
            run_prim(p, child_rng)

        def out_val(p):
            v = values[id(p)]
            if hasattr(v, "ndim") and v.ndim == 4:
                v = jnp.transpose(v, (0, 3, 1, 2))  # back to NCHW boundary
            return v

        outs = [out_val(p) for p in self._outs]
        single = getattr(self.graph, "_single_output", True)
        return (outs[0] if single else outs), new_state

    def __repr__(self) -> str:
        return f"FusedGraph({len(self._edges)} fused edges, {self.graph!r})"


# Modules whose apply is layout-indifferent on NHWC values. Reshape/View
# are here for the conv-zoo pattern only — they follow global pooling, where
# the spatial dims are already 1×1 and NHWC flatten equals NCHW flatten. A
# Reshape over real spatial extent is layout-sensitive; such a graph must
# not be wrapped (parity tests catch it loudly).
_AGNOSTIC = {
    "ReLU", "ReLU6", "Tanh", "Sigmoid", "Dropout", "CAddTable", "CMulTable",
    "Identity", "LogSoftMax", "Linear", "Reshape", "View",
}


def _conv_nhwc(m: SpatialConvolution, params, x):
    import jax
    import jax.lax as lax

    if (m.kernel_w == 1 and m.kernel_h == 1 and m.pad_w == 0
            and m.pad_h == 0 and m.n_group == 1):
        # 1×1 conv as a dot contracting C on the 4-D value — XLA
        # prologue/epilogue fuses elementwise neighbors into a dot but NOT
        # into a convolution op (measured: the dot form is 1.5-2.4×
        # faster, PERF_ANALYSIS_r3.md); a stride just slices rows first.
        # No reshape: that would physically repack the tiled NHWC layout.
        if m.stride_h != 1 or m.stride_w != 1:
            x = x[:, ::m.stride_h, ::m.stride_w, :]
        w2 = params["weight"][:, :, 0, 0].T            # (C, K)
        out = jax.lax.dot_general(
            x, w2.astype(x.dtype),
            dimension_numbers=(((3,), (0,)), ((), ())))
    else:
        out = lax.conv_general_dilated(
            x, params["weight"],
            window_strides=(m.stride_h, m.stride_w),
            padding=m._padding(),
            dimension_numbers=("NHWC", "OIHW", "NHWC"),
            feature_group_count=m.n_group,
        )
    if m.with_bias:
        out = out + params["bias"][None, None, None, :]
    return out


def _pool_nhwc(m, x):
    import jax.lax as lax
    import jax.numpy as jnp

    ph, pw = m._pads(x.shape[1], x.shape[2])
    if isinstance(m, SpatialMaxPooling):
        return lax.reduce_window(
            x, -jnp.inf, lax.max,
            window_dimensions=(1, m.kh, m.kw, 1),
            window_strides=(1, m.dh, m.dw, 1),
            padding=((0, 0), ph, pw, (0, 0)),
        )
    # average pooling (mirrors SpatialAveragePooling.apply)
    if m.global_pooling:
        kh, kw = x.shape[1], x.shape[2]
    else:
        kh, kw = m.kh, m.kw
    saved = (m.kh, m.kw)
    m.kh, m.kw = kh, kw
    ph, pw = m._pads(x.shape[1], x.shape[2])
    m.kh, m.kw = saved
    sums = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, m.dh, m.dw, 1),
        padding=((0, 0), ph, pw, (0, 0)),
    )
    if not m.divide:
        return sums
    if m.count_include_pad:
        return sums / float(kh * kw)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, m.dh, m.dw, 1),
        padding=((0, 0), ph, pw, (0, 0)),
    )
    return sums / counts


def maybe_fuse(model):
    """Wrap a Graph in FusedGraph when it contains at least one fusable
    edge; otherwise return it unchanged. The TPU-engine entry point."""
    if not isinstance(model, Graph):
        return model
    fused = FusedGraph(model)
    return fused if fused._edges else model
