"""Fine-grained TF-style ops (the ``nn/ops`` layer of the reference).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/ops/*.scala`` (~100
small op classes: ``Conv2D``, ``BiasAdd``, pooling, arithmetic, shape ops) —
they exist to EXECUTE imported TensorFlow graphs, and ``utils/tf/
TensorflowLoader.scala`` maps GraphDef nodes onto them.

TPU-native: each op is a thin ``AbstractModule`` over the matching
``jax.lax``/``jnp`` primitive in TF's native NHWC layout (no transposes at
import time; XLA picks layouts). Weight-carrying ops hold their imported
constants as ordinary params, so imported graphs remain trainable exactly
like reference-imported models.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn.module import AbstractModule, TensorModule


class ParameterOp(TensorModule):
    """An imported constant promoted to a trainable parameter (the loader
    uses this for Variables/Consts feeding weight slots)."""

    def __init__(self, value) -> None:
        super().__init__()
        self._value = np.asarray(value)

    def init_params(self, rng):
        return {"value": self._value}

    def apply(self, params, input, state=None, training=False, rng=None):
        return params["value"], state


class ConstOp(TensorModule):
    """A non-trainable imported constant (shapes, axes, paddings)."""

    def __init__(self, value) -> None:
        super().__init__()
        self.value = np.asarray(value)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.asarray(self.value), state


class Conv2D(AbstractModule):
    """TF Conv2D: input NHWC, filter HWIO. Table input [x, filter]."""

    def __init__(self, strides: Sequence[int], padding: str = "SAME") -> None:
        super().__init__()
        self.strides = tuple(strides)  # full NHWC strides or (sh, sw)
        self.padding = padding

    def _hw_strides(self) -> Tuple[int, int]:
        s = self.strides
        return (s[1], s[2]) if len(s) == 4 else (s[0], s[1])

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        x, w = input
        out = lax.conv_general_dilated(
            x, w, window_strides=self._hw_strides(), padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out, state


class DepthwiseConv2dNative(AbstractModule):
    """TF depthwise conv: filter HWIM (multiplier M)."""

    def __init__(self, strides: Sequence[int], padding: str = "SAME") -> None:
        super().__init__()
        self.strides = tuple(strides)
        self.padding = padding

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        x, w = input
        h, wk, c, m = w.shape
        s = self.strides
        hw = (s[1], s[2]) if len(s) == 4 else (s[0], s[1])
        out = lax.conv_general_dilated(
            x, w.reshape(h, wk, 1, c * m), window_strides=hw,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )
        return out, state


class BiasAdd(AbstractModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        x, b = input
        return x + b, state


class MatMul(AbstractModule):
    def __init__(self, transpose_a: bool = False, transpose_b: bool = False) -> None:
        super().__init__()
        self.transpose_a = transpose_a
        self.transpose_b = transpose_b

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        a, b = input
        if self.transpose_a:
            a = a.T
        if self.transpose_b:
            b = b.T
        return jnp.matmul(a, b), state


class _Pool2D(TensorModule):
    def __init__(self, ksize: Sequence[int], strides: Sequence[int],
                 padding: str = "VALID") -> None:
        super().__init__()
        k, s = tuple(ksize), tuple(strides)
        self.k = (k[1], k[2]) if len(k) == 4 else (k[0], k[1])
        self.s = (s[1], s[2]) if len(s) == 4 else (s[0], s[1])
        self.padding = padding

    def _window(self, x):
        return (1, self.k[0], self.k[1], 1), (1, self.s[0], self.s[1], 1)


class MaxPool(_Pool2D):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        dims, strides = self._window(input)
        return lax.reduce_window(
            input, -jnp.inf, lax.max, dims, strides, self.padding), state


class AvgPool(_Pool2D):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        dims, strides = self._window(input)
        sums = lax.reduce_window(input, 0.0, lax.add, dims, strides, self.padding)
        ones = jnp.ones_like(input)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, self.padding)
        return sums / counts, state


class FusedBatchNorm(AbstractModule):
    """Inference-mode TF FusedBatchNorm: [x, scale, offset, mean, var]."""

    def __init__(self, epsilon: float = 1e-3) -> None:
        super().__init__()
        self.epsilon = epsilon

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, scale, offset, mean, var = input
        inv = scale / jnp.sqrt(var + self.epsilon)
        return x * inv + (offset - mean * inv), state


class Reshape(AbstractModule):
    """TF Reshape: [x, shape] (shape may contain -1; a leading -1 keeps the
    batch dynamic)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        x, shape = input
        target = [int(v) for v in np.asarray(shape).reshape(-1)]
        return x.reshape(target), state


class Squeeze(TensorModule):
    def __init__(self, axis: Optional[Sequence[int]] = None) -> None:
        super().__init__()
        self.axis = tuple(axis) if axis else None

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.squeeze(input, self.axis), state


class ExpandDims(AbstractModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axis = input
        return jnp.expand_dims(x, int(np.asarray(axis))), state


class ConcatV2(AbstractModule):
    """TF ConcatV2: [x1, ..., xn, axis]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        *xs, axis = input
        return jnp.concatenate(xs, int(np.asarray(axis))), state


class Pad(AbstractModule):
    """TF Pad: [x, paddings (ndim, 2)]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, pads = input
        pads = [(int(a), int(b)) for a, b in np.asarray(pads)]
        return jnp.pad(x, pads), state


class Mean(AbstractModule):
    """TF Mean: [x, axes]."""

    def __init__(self, keep_dims: bool = False) -> None:
        super().__init__()
        self.keep_dims = keep_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axes = input
        axes = tuple(int(a) for a in np.asarray(axes).reshape(-1))
        return jnp.mean(x, axis=axes, keepdims=self.keep_dims), state


class _Binary(AbstractModule):
    def op(self, a, b):
        raise NotImplementedError

    def apply(self, params, input, state=None, training=False, rng=None):
        a, b = input
        return self.op(a, b), state


class Add(_Binary):
    def op(self, a, b):
        return a + b


class Sub(_Binary):
    def op(self, a, b):
        return a - b


class Mul(_Binary):
    def op(self, a, b):
        return a * b


class RealDiv(_Binary):
    def op(self, a, b):
        return a / b


class Maximum(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.maximum(a, b)


class Rsqrt(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        return lax.rsqrt(input), state


class Softmax(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.softmax(input, axis=-1), state


# ---------------------------------------------------------------------------
# extended op set (the rest of the reference's ~100 nn/ops classes)
# ---------------------------------------------------------------------------

class _Unary(TensorModule):
    def op(self, x):
        raise NotImplementedError

    def apply(self, params, input, state=None, training=False, rng=None):
        return self.op(input), state


class Minimum(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.minimum(a, b)


class Pow(_Binary):
    def op(self, a, b):
        return a ** b


class FloorDiv(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.floor_divide(a, b)


class FloorMod(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.mod(a, b)


class SquaredDifference(_Binary):
    def op(self, a, b):
        return (a - b) * (a - b)


class Greater(_Binary):
    def op(self, a, b):
        return a > b


class GreaterEqual(_Binary):
    def op(self, a, b):
        return a >= b


class Less(_Binary):
    def op(self, a, b):
        return a < b


class LessEqual(_Binary):
    def op(self, a, b):
        return a <= b


class Equal(_Binary):
    def op(self, a, b):
        return a == b


class NotEqual(_Binary):
    def op(self, a, b):
        return a != b


class LogicalAnd(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.logical_and(a, b)


class LogicalOr(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.logical_or(a, b)


class LogicalNot(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.logical_not(x)


class Abs(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.abs(x)


class Floor(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.floor(x)


class Ceil(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.ceil(x)


class Round(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.round(x)


class Sign(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.sign(x)


class Elu(_Unary):
    def op(self, x):
        import jax

        return jax.nn.elu(x)


class Selu(_Unary):
    def op(self, x):
        import jax

        return jax.nn.selu(x)


class Erf(_Unary):
    def op(self, x):
        import jax

        return jax.scipy.special.erf(x)


class Reciprocal(_Unary):
    def op(self, x):
        return 1.0 / x


class Cast(_Unary):
    """TF Cast; dtype resolved at import from the DstT attr."""

    def __init__(self, dtype) -> None:
        super().__init__()
        self.dtype = dtype

    def op(self, x):
        import jax.numpy as jnp

        return jnp.asarray(x).astype(self.dtype)


class Transpose(AbstractModule):
    """TF Transpose: [x, perm]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, perm = input
        return jnp.transpose(x, tuple(int(p) for p in np.asarray(perm))), state


class TileOp(AbstractModule):
    """TF Tile: [x, multiples]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, mult = input
        return jnp.tile(x, tuple(int(m) for m in np.asarray(mult))), state


class SliceOp(AbstractModule):
    """TF Slice: [x, begin, size] (size −1 = to the end)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        x, begin, size = input
        begin = [int(b) for b in np.asarray(begin)]
        size = [int(s) for s in np.asarray(size)]
        idx = tuple(
            slice(b, None if s == -1 else b + s)
            for b, s in zip(begin, size)
        )
        return x[idx], state


class StridedSlice(AbstractModule):
    """TF StridedSlice: [x, begin, end, strides] honoring all five masks
    (begin/end/ellipsis/new-axis/shrink)."""

    def __init__(self, begin_mask: int = 0, end_mask: int = 0,
                 shrink_axis_mask: int = 0, new_axis_mask: int = 0,
                 ellipsis_mask: int = 0) -> None:
        super().__init__()
        self.begin_mask = begin_mask
        self.end_mask = end_mask
        self.shrink_axis_mask = shrink_axis_mask
        self.new_axis_mask = new_axis_mask
        self.ellipsis_mask = ellipsis_mask

    def apply(self, params, input, state=None, training=False, rng=None):
        x, begin, end, strides = input
        begin = [int(b) for b in np.asarray(begin)]
        end = [int(e) for e in np.asarray(end)]
        strides = [int(s) for s in np.asarray(strides)]
        idx = []
        for i in range(len(begin)):
            if (self.new_axis_mask >> i) & 1:
                idx.append(None)          # np.newaxis
            elif (self.ellipsis_mask >> i) & 1:
                idx.append(Ellipsis)
            elif (self.shrink_axis_mask >> i) & 1:
                idx.append(begin[i])
            else:
                b = None if (self.begin_mask >> i) & 1 else begin[i]
                e = None if (self.end_mask >> i) & 1 else end[i]
                idx.append(slice(b, e, strides[i]))
        return x[tuple(idx)], state


class PackOp(AbstractModule):
    """TF Pack/Stack: N inputs → stacked along axis."""

    def __init__(self, axis: int = 0) -> None:
        super().__init__()
        self.axis = axis

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        xs = input if isinstance(input, (list, tuple)) else [input]
        return jnp.stack(list(xs), axis=self.axis), state


class Unpack(AbstractModule):
    """TF Unpack/Unstack: tensor → table of slices along axis."""

    def __init__(self, axis: int = 0, num: Optional[int] = None) -> None:
        super().__init__()
        self.axis = axis
        self.num = num

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        n = self.num or input.shape[self.axis]
        parts = jnp.split(input, n, axis=self.axis)
        return [jnp.squeeze(p, self.axis) for p in parts], state


class SplitOp(AbstractModule):
    """TF Split: [axis, x] → table of num_split equal parts."""

    def __init__(self, num_split: int) -> None:
        super().__init__()
        self.num_split = num_split

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        axis, x = input
        return list(jnp.split(x, self.num_split, int(np.asarray(axis)))), state


class SplitV(AbstractModule):
    """TF SplitV: [x, size_splits, axis] → table of uneven parts."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, sizes, axis = input
        sizes = [int(s) for s in np.asarray(sizes)]
        cuts = list(np.cumsum(sizes[:-1]))
        return list(jnp.split(x, cuts, int(np.asarray(axis)))), state


class Fill(AbstractModule):
    """TF Fill: [dims, value]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        dims, value = input
        shape = tuple(int(d) for d in np.asarray(dims))
        return jnp.full(shape, value), state


class Select(AbstractModule):
    """TF Select/SelectV2: [cond, a, b]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        cond, a, b = input
        return jnp.where(cond, a, b), state


class ClipByValue(AbstractModule):
    """TF ClipByValue: [x, lo, hi]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, lo, hi = input
        return jnp.clip(x, lo, hi), state


class _Reduce(AbstractModule):
    """Shared [x, axes] reduction with keep_dims."""

    def __init__(self, keep_dims: bool = False) -> None:
        super().__init__()
        self.keep_dims = keep_dims

    def red(self, x, axes, keepdims):
        raise NotImplementedError

    def apply(self, params, input, state=None, training=False, rng=None):
        x, axes = input
        axes = tuple(int(a) for a in np.asarray(axes).reshape(-1))
        return self.red(x, axes, self.keep_dims), state


class Sum(_Reduce):
    def red(self, x, axes, keepdims):
        import jax.numpy as jnp

        return jnp.sum(x, axis=axes, keepdims=keepdims)


class Max(_Reduce):
    def red(self, x, axes, keepdims):
        import jax.numpy as jnp

        return jnp.max(x, axis=axes, keepdims=keepdims)


class Min(_Reduce):
    def red(self, x, axes, keepdims):
        import jax.numpy as jnp

        return jnp.min(x, axis=axes, keepdims=keepdims)


class Prod(_Reduce):
    def red(self, x, axes, keepdims):
        import jax.numpy as jnp

        return jnp.prod(x, axis=axes, keepdims=keepdims)


class ArgMax(AbstractModule):
    """TF ArgMax: [x, axis] → int indices."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axis = input
        return jnp.argmax(x, int(np.asarray(axis))), state


class DepthToSpace(TensorModule):
    """NHWC DepthToSpace with block size b: (N,H,W,C·b²) → (N,H·b,W·b,C)."""

    def __init__(self, block_size: int) -> None:
        super().__init__()
        self.b = block_size

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        n, h, w, c = input.shape
        b = self.b
        x = input.reshape(n, h, w, b, b, c // (b * b))
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return x.reshape(n, h * b, w * b, c // (b * b)), state


class SpaceToDepth(TensorModule):
    """NHWC SpaceToDepth with block size b: (N,H·b,W·b,C) → (N,H,W,C·b²)."""

    def __init__(self, block_size: int) -> None:
        super().__init__()
        self.b = block_size

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        n, hb, wb, c = input.shape
        b = self.b
        x = input.reshape(n, hb // b, b, wb // b, b, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return x.reshape(n, hb // b, wb // b, c * b * b), state


class GatherV2(AbstractModule):
    """TF GatherV2: [params, indices, axis]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        p, idx, axis = input
        return jnp.take(p, jnp.asarray(idx, jnp.int32),
                        axis=int(np.asarray(axis))), state


class OneHot(AbstractModule):
    """TF OneHot: [indices, depth, on_value, off_value]; axis attr."""

    def __init__(self, axis: int = -1) -> None:
        super().__init__()
        self.axis = axis

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        idx, depth, on, off = input
        oh = jax.nn.one_hot(jnp.asarray(idx, jnp.int32),
                            int(np.asarray(depth)), axis=self.axis)
        return oh * on + (1.0 - oh) * off, state


class BatchMatMul(AbstractModule):
    """TF BatchMatMul(V2) with adjoint flags."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False) -> None:
        super().__init__()
        self.adj_x = adj_x
        self.adj_y = adj_y

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        a, b = input
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), state


class Cumsum(AbstractModule):
    """TF Cumsum: [x, axis] with exclusive/reverse attrs."""

    def __init__(self, exclusive: bool = False, reverse: bool = False) -> None:
        super().__init__()
        self.exclusive = exclusive
        self.reverse = reverse

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axis = input
        ax = int(np.asarray(axis))
        if self.reverse:
            x = jnp.flip(x, ax)
        out = jnp.cumsum(x, axis=ax)
        if self.exclusive:
            out = out - x
        if self.reverse:
            out = jnp.flip(out, ax)
        return out, state


class RangeOp(AbstractModule):
    """TF Range: [start, limit, delta]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        start, limit, delta = (np.asarray(v) for v in input)
        return jnp.arange(float(start), float(limit), float(delta)), state


class ZerosLike(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.zeros_like(x)


class OnesLike(_Unary):
    def op(self, x):
        import jax.numpy as jnp

        return jnp.ones_like(x)


class Shape(TensorModule):
    """TF Shape — static under XLA, returned as a constant vector."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.asarray(input.shape, jnp.int32), state


class LogSoftmax(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.log_softmax(input, axis=-1), state


class TopKV2(AbstractModule):
    """TF TopKV2: [x, k] → table [values, indices] (multi-output ports)."""

    def __init__(self, sorted: bool = True) -> None:
        super().__init__()

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        x, k = input
        vals, idx = lax.top_k(x, int(np.asarray(k)))
        return [vals, idx], state


# ----------------------------------------------------------------------------
# Control flow (reference nn/ops control-flow set — SURVEY §2.2:
# Switch/Merge/Enter/Exit/NextIteration/LoopCond). TPU-native lowering:
# a TF v1 while frame collapses to ONE ``lax.while_loop`` (TFWhile below,
# assembled by the loader's frame extractor); a v1 cond's Switch/Merge pair
# lowers to compute-both-branches + ``jnp.where`` select (valid for the
# pure dataflow graphs the loader imports — no side effects to gate).
# ----------------------------------------------------------------------------


class SwitchOp(AbstractModule):
    """TF Switch: [data, pred] → table (output_false, output_true).

    Dataflow lowering: both ports carry ``data``; the branch selection
    happens at the matching :class:`CondMerge` (select semantics). The
    dead-branch suppression of TF's executor is unnecessary here — both
    branches are pure and XLA DCEs whichever the consumer ignores."""

    def apply(self, params, input, state=None, training=False, rng=None):
        data, _pred = input
        return [data, data], state


class CondMerge(AbstractModule):
    """TF Merge under a cond region: [false_value, true_value, pred] →
    ``jnp.where(pred, true_v, false_v)`` (the loader routes the
    controlling Switch predicate in as the third input).

    Limitation: both branches are COMPUTED (select, not ``lax.cond``), so
    if the dead branch produces NaN/inf intermediates (e.g. a div-by-zero
    the cond was guarding), gradients through the imported graph can pick
    up NaN via the ``0 * NaN`` cotangent path even though the forward is
    clean. Graphs that need dead-branch gradient suppression should import
    through the v2 functional path (:class:`TFCond` lowers to
    ``lax.cond``, which differentiates only the live branch)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        false_v, true_v, pred = input
        return jnp.where(pred, true_v, false_v), state


class TFWhile(AbstractModule):
    """A whole TF while-loop (v1 Enter/Merge/Switch/Exit/NextIteration/
    LoopCond frame, or a v2 functional ``While``) as one ``lax.while_loop``.

    ``cond_fn(carry, consts) -> bool`` and ``body_fn(carry, consts) ->
    carry`` are built by the loader's GraphDef interpreter; ``input`` is the
    table of loop-variable initial values (the Enter inputs) followed by
    ``n_consts`` loop-invariant values (``Enter(is_constant=true)``)."""

    def __init__(self, cond_fn, body_fn, n_vars: int, n_consts: int = 0) -> None:
        super().__init__()
        self.cond_fn = cond_fn
        self.body_fn = body_fn
        self.n_vars = n_vars
        self.n_consts = n_consts

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        vals = tuple(input) if isinstance(input, (list, tuple)) else (input,)
        # loop carry must be jax types with stable dtypes across iterations
        vals = tuple(jnp.asarray(v) for v in vals)
        carry, consts = vals[: self.n_vars], vals[self.n_vars:]
        out = lax.while_loop(
            lambda c: self.cond_fn(c, consts),
            lambda c: self.body_fn(c, consts),
            carry,
        )
        # always a table: consumers address loop vars by port (SelectTable)
        return list(out), state


class TFCond(AbstractModule):
    """TF v2 functional If/StatelessIf as ``lax.cond``: input table
    ``[pred, *branch_args]``; ``then_fn(args)``/``else_fn(args)`` return
    the branch output tuple (built by the loader's FunctionDef
    interpreter)."""

    def __init__(self, then_fn, else_fn, n_out: int) -> None:
        super().__init__()
        self.then_fn = then_fn
        self.else_fn = else_fn
        self.n_out = n_out

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        vals = tuple(input) if isinstance(input, (list, tuple)) else (input,)
        pred, *args = vals
        args = tuple(jnp.asarray(a) for a in args)
        out = lax.cond(jnp.asarray(pred).reshape(()),
                       self.then_fn, self.else_fn, args)
        # always a table: consumers address branch outputs by port
        return list(out), state


# structural v1 frame ops: standalone they are identity (the loader's frame
# extractor consumes them before lowering; these exist so a hand-built
# graph of raw control-flow nodes still loads)
class EnterOp(AbstractModule):
    def __init__(self, frame_name: str = "", is_constant: bool = False) -> None:
        super().__init__()
        self.frame_name = frame_name
        self.is_constant = is_constant

    def apply(self, params, input, state=None, training=False, rng=None):
        return input, state


class ExitOp(EnterOp):
    pass


class NextIterationOp(EnterOp):
    pass


class LoopCondOp(EnterOp):
    pass


# round-2 widening: image-resize / padding / space-batch ops common in
# frozen inference graphs (segmentation, detection, dilated-conv graphs)


class PadV2(AbstractModule):
    """TF PadV2: [x, paddings, constant_value]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, pads, value = input
        pads = [(int(a), int(b)) for a, b in np.asarray(pads)]
        return jnp.pad(x, pads, constant_values=np.asarray(value).item()), state


class MirrorPad(AbstractModule):
    """TF MirrorPad: [x, paddings]; mode REFLECT or SYMMETRIC."""

    def __init__(self, mode: str = "REFLECT") -> None:
        super().__init__()
        self.mode = mode.lower()

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, pads = input
        pads = [(int(a), int(b)) for a, b in np.asarray(pads)]
        return jnp.pad(x, pads, mode=self.mode), state


class ResizeBilinear(AbstractModule):
    """TF ResizeBilinear: [images NHWC, size (2,)]; static size."""

    def __init__(self, align_corners: bool = False,
                 half_pixel_centers: bool = False) -> None:
        super().__init__()
        self.align_corners = align_corners
        self.half_pixel_centers = half_pixel_centers

    def _coords(self, out_n, in_n, dtype):
        import jax.numpy as jnp

        out_idx = jnp.arange(out_n, dtype=dtype)
        if self.align_corners and out_n > 1:
            return out_idx * ((in_n - 1) / (out_n - 1))
        scale = in_n / out_n
        if self.half_pixel_centers:
            return jnp.maximum((out_idx + 0.5) * scale - 0.5, 0.0)
        return out_idx * scale

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, size = input
        h_out, w_out = (int(v) for v in np.asarray(size))
        n, h_in, w_in, c = x.shape
        dtype = jnp.float32
        # TF ResizeBilinear always interpolates and returns float32, even
        # for integer (uint8 image) inputs
        x = x.astype(jnp.float32)

        def interp(x, coords, axis):
            lo = jnp.floor(coords).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, x.shape[axis] - 1)
            frac = (coords - lo).astype(x.dtype)
            shape = [1] * x.ndim
            shape[axis] = -1
            frac = frac.reshape(shape)
            return (jnp.take(x, lo, axis=axis) * (1 - frac)
                    + jnp.take(x, hi, axis=axis) * frac)

        x = interp(x, self._coords(h_out, h_in, dtype), 1)
        x = interp(x, self._coords(w_out, w_in, dtype), 2)
        return x, state


class ResizeNearestNeighbor(ResizeBilinear):
    """TF ResizeNearestNeighbor: [images NHWC, size]. TF's NN kernel uses
    ITS OWN scalers (not the bilinear ones): half_pixel_centers →
    floor((out+0.5)·scale) with no −0.5 shift, align_corners → round half
    AWAY from zero of out·(in−1)/(out−1)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, size = input
        h_out, w_out = (int(v) for v in np.asarray(size))
        n, h_in, w_in, c = x.shape

        def pick(out_n, in_n):
            out_idx = jnp.arange(out_n, dtype=jnp.float32)
            if self.align_corners and out_n > 1:
                coords = out_idx * ((in_n - 1) / (out_n - 1))
                # roundf semantics: half away from zero (coords >= 0 here)
                idx = jnp.floor(coords + 0.5)
            elif self.half_pixel_centers:
                idx = jnp.floor((out_idx + 0.5) * (in_n / out_n))
            else:
                idx = jnp.floor(out_idx * (in_n / out_n))
            return idx.astype(jnp.int32).clip(0, in_n - 1)

        hc = pick(h_out, h_in)
        wc = pick(w_out, w_in)
        return jnp.take(jnp.take(x, hc, axis=1), wc, axis=2), state


class SpaceToBatchND(AbstractModule):
    """TF SpaceToBatchND: [x, block_shape, paddings] — the op TF emits
    around convs with dilation (atrous wrappers)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, block, pads = input
        block = [int(b) for b in np.asarray(block)]
        pads = np.asarray(pads)
        widths = [(0, 0)] + [(int(a), int(b)) for a, b in pads]
        widths += [(0, 0)] * (x.ndim - len(widths))
        x = jnp.pad(x, widths)
        n = x.shape[0]
        spatial = x.shape[1:1 + len(block)]
        rest = x.shape[1 + len(block):]
        # (N, s1/b1, b1, s2/b2, b2, ..., rest) -> blocks to batch
        shape = [n]
        for s, b in zip(spatial, block):
            shape += [s // b, b]
        x = x.reshape(shape + list(rest))
        block_axes = [2 + 2 * i for i in range(len(block))]
        keep_axes = [1 + 2 * i for i in range(len(block))]
        perm = (block_axes + [0] + keep_axes
                + list(range(1 + 2 * len(block), x.ndim)))
        x = x.transpose(perm)
        out_spatial = [s // b for s, b in zip(spatial, block)]
        return x.reshape([n * int(np.prod(block))] + out_spatial
                         + list(rest)), state


class BatchToSpaceND(AbstractModule):
    """TF BatchToSpaceND: [x, block_shape, crops] — inverse of
    SpaceToBatchND."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, block, crops = input
        block = [int(b) for b in np.asarray(block)]
        crops = np.asarray(crops)
        nb = int(np.prod(block))
        n = x.shape[0] // nb
        spatial = x.shape[1:1 + len(block)]
        rest = x.shape[1 + len(block):]
        x = x.reshape(block + [n] + list(spatial) + list(rest))
        nd = len(block)
        perm = [nd]
        for i in range(nd):
            perm += [nd + 1 + i, i]
        perm += list(range(2 * nd + 1, x.ndim))
        x = x.transpose(perm)
        x = x.reshape([n] + [s * b for s, b in zip(spatial, block)]
                      + list(rest))
        slices = [slice(None)]
        for (lo, hi), s, b in zip(crops, spatial, block):
            slices.append(slice(int(lo), s * b - int(hi)))
        return x[tuple(slices)], state


class RankOp(AbstractModule):
    """TF Rank: static ndim as int32 scalar."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.asarray(np.ndim(input) if not hasattr(input, "ndim")
                           else input.ndim, jnp.int32), state


class SizeOp(AbstractModule):
    """TF Size: static element count as int32 scalar."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.asarray(int(np.prod(input.shape)), jnp.int32), state


class _Elementwise(TensorModule):
    """One-jnp-function elementwise op (Sin/Cos/Log1p/... family)."""

    _fn = None

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return getattr(jnp, self._fn)(input), state


class Sin(_Elementwise):
    _fn = "sin"


class Cos(_Elementwise):
    _fn = "cos"


class Tan(_Elementwise):
    _fn = "tan"


class Asin(_Elementwise):
    _fn = "arcsin"


class Acos(_Elementwise):
    _fn = "arccos"


class Atan(_Elementwise):
    _fn = "arctan"


class Sinh(_Elementwise):
    _fn = "sinh"


class Cosh(_Elementwise):
    _fn = "cosh"


class Log1p(_Elementwise):
    _fn = "log1p"


class Expm1(_Elementwise):
    _fn = "expm1"


class IsNan(_Elementwise):
    _fn = "isnan"


class IsInf(_Elementwise):
    _fn = "isinf"


class IsFinite(_Elementwise):
    _fn = "isfinite"


class LRN(AbstractModule):
    """TF LRN over NHWC input (depth_radius window on the channel axis) —
    the TF dialect of the core SpatialCrossMapLRN (which is NCHW and uses
    size = 2*radius+1 with alpha pre-divided)."""

    def __init__(self, depth_radius: int = 5, bias: float = 1.0,
                 alpha: float = 1.0, beta: float = 0.5) -> None:
        super().__init__()
        self.depth_radius = depth_radius
        self.bias = bias
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        r = self.depth_radius
        window_sum = lax.reduce_window(
            input * input, 0.0, lax.add,
            window_dimensions=(1, 1, 1, 2 * r + 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (r, r)),
        )
        return input / (self.bias + self.alpha * window_sum) ** self.beta, state
