"""Fine-grained TF-style ops (the ``nn/ops`` layer of the reference).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/ops/*.scala`` (~100
small op classes: ``Conv2D``, ``BiasAdd``, pooling, arithmetic, shape ops) —
they exist to EXECUTE imported TensorFlow graphs, and ``utils/tf/
TensorflowLoader.scala`` maps GraphDef nodes onto them.

TPU-native: each op is a thin ``AbstractModule`` over the matching
``jax.lax``/``jnp`` primitive in TF's native NHWC layout (no transposes at
import time; XLA picks layouts). Weight-carrying ops hold their imported
constants as ordinary params, so imported graphs remain trainable exactly
like reference-imported models.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn.module import AbstractModule, TensorModule


class ParameterOp(TensorModule):
    """An imported constant promoted to a trainable parameter (the loader
    uses this for Variables/Consts feeding weight slots)."""

    def __init__(self, value) -> None:
        super().__init__()
        self._value = np.asarray(value)

    def init_params(self, rng):
        return {"value": self._value}

    def apply(self, params, input, state=None, training=False, rng=None):
        return params["value"], state


class ConstOp(TensorModule):
    """A non-trainable imported constant (shapes, axes, paddings)."""

    def __init__(self, value) -> None:
        super().__init__()
        self.value = np.asarray(value)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.asarray(self.value), state


class Conv2D(AbstractModule):
    """TF Conv2D: input NHWC, filter HWIO. Table input [x, filter]."""

    def __init__(self, strides: Sequence[int], padding: str = "SAME") -> None:
        super().__init__()
        self.strides = tuple(strides)  # full NHWC strides or (sh, sw)
        self.padding = padding

    def _hw_strides(self) -> Tuple[int, int]:
        s = self.strides
        return (s[1], s[2]) if len(s) == 4 else (s[0], s[1])

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        x, w = input
        out = lax.conv_general_dilated(
            x, w, window_strides=self._hw_strides(), padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out, state


class DepthwiseConv2dNative(AbstractModule):
    """TF depthwise conv: filter HWIM (multiplier M)."""

    def __init__(self, strides: Sequence[int], padding: str = "SAME") -> None:
        super().__init__()
        self.strides = tuple(strides)
        self.padding = padding

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        x, w = input
        h, wk, c, m = w.shape
        s = self.strides
        hw = (s[1], s[2]) if len(s) == 4 else (s[0], s[1])
        out = lax.conv_general_dilated(
            x, w.reshape(h, wk, 1, c * m), window_strides=hw,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )
        return out, state


class BiasAdd(AbstractModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        x, b = input
        return x + b, state


class MatMul(AbstractModule):
    def __init__(self, transpose_a: bool = False, transpose_b: bool = False) -> None:
        super().__init__()
        self.transpose_a = transpose_a
        self.transpose_b = transpose_b

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        a, b = input
        if self.transpose_a:
            a = a.T
        if self.transpose_b:
            b = b.T
        return jnp.matmul(a, b), state


class _Pool2D(TensorModule):
    def __init__(self, ksize: Sequence[int], strides: Sequence[int],
                 padding: str = "VALID") -> None:
        super().__init__()
        k, s = tuple(ksize), tuple(strides)
        self.k = (k[1], k[2]) if len(k) == 4 else (k[0], k[1])
        self.s = (s[1], s[2]) if len(s) == 4 else (s[0], s[1])
        self.padding = padding

    def _window(self, x):
        return (1, self.k[0], self.k[1], 1), (1, self.s[0], self.s[1], 1)


class MaxPool(_Pool2D):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        dims, strides = self._window(input)
        return lax.reduce_window(
            input, -jnp.inf, lax.max, dims, strides, self.padding), state


class AvgPool(_Pool2D):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        dims, strides = self._window(input)
        sums = lax.reduce_window(input, 0.0, lax.add, dims, strides, self.padding)
        ones = jnp.ones_like(input)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, self.padding)
        return sums / counts, state


class FusedBatchNorm(AbstractModule):
    """Inference-mode TF FusedBatchNorm: [x, scale, offset, mean, var]."""

    def __init__(self, epsilon: float = 1e-3) -> None:
        super().__init__()
        self.epsilon = epsilon

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, scale, offset, mean, var = input
        inv = scale / jnp.sqrt(var + self.epsilon)
        return x * inv + (offset - mean * inv), state


class Reshape(AbstractModule):
    """TF Reshape: [x, shape] (shape may contain -1; a leading -1 keeps the
    batch dynamic)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        x, shape = input
        target = [int(v) for v in np.asarray(shape).reshape(-1)]
        return x.reshape(target), state


class Squeeze(TensorModule):
    def __init__(self, axis: Optional[Sequence[int]] = None) -> None:
        super().__init__()
        self.axis = tuple(axis) if axis else None

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.squeeze(input, self.axis), state


class ExpandDims(AbstractModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axis = input
        return jnp.expand_dims(x, int(np.asarray(axis))), state


class ConcatV2(AbstractModule):
    """TF ConcatV2: [x1, ..., xn, axis]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        *xs, axis = input
        return jnp.concatenate(xs, int(np.asarray(axis))), state


class Pad(AbstractModule):
    """TF Pad: [x, paddings (ndim, 2)]."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, pads = input
        pads = [(int(a), int(b)) for a, b in np.asarray(pads)]
        return jnp.pad(x, pads), state


class Mean(AbstractModule):
    """TF Mean: [x, axes]."""

    def __init__(self, keep_dims: bool = False) -> None:
        super().__init__()
        self.keep_dims = keep_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, axes = input
        axes = tuple(int(a) for a in np.asarray(axes).reshape(-1))
        return jnp.mean(x, axis=axes, keepdims=self.keep_dims), state


class _Binary(AbstractModule):
    def op(self, a, b):
        raise NotImplementedError

    def apply(self, params, input, state=None, training=False, rng=None):
        a, b = input
        return self.op(a, b), state


class Add(_Binary):
    def op(self, a, b):
        return a + b


class Sub(_Binary):
    def op(self, a, b):
        return a - b


class Mul(_Binary):
    def op(self, a, b):
        return a * b


class RealDiv(_Binary):
    def op(self, a, b):
        return a / b


class Maximum(_Binary):
    def op(self, a, b):
        import jax.numpy as jnp

        return jnp.maximum(a, b)


class Rsqrt(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        return lax.rsqrt(input), state


class Softmax(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.softmax(input, axis=-1), state
