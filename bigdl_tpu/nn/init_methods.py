"""Initialization methods.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/InitializationMethod.scala``
— ``RandomUniform``, ``RandomNormal``, ``Xavier``, ``MsraFiller``,
``BilinearFiller``, ``Zeros``, ``Ones``, ``ConstInitMethod``; layers expose
``setInitMethod(weightInit, biasInit)``. The ResNet zoo uses MSRA.

Fan computation follows the Torch convention the reference uses: for a conv
weight of shape (out, in, kH, kW), fan_in = in*kH*kW, fan_out = out*kH*kW;
for a linear weight (out, in), fan_in = in, fan_out = out.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 2:
        fan_out, fan_in = shape
    elif len(shape) >= 3:
        receptive = int(np.prod(shape[2:]))
        fan_out = shape[0] * receptive
        fan_in = shape[1] * receptive
    elif len(shape) == 1:
        fan_in = fan_out = shape[0]
    else:
        fan_in = fan_out = 1
    return int(fan_in), int(fan_out)


class InitializationMethod:
    def init(self, rng, shape, dtype="float32"):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def init(self, rng, shape, dtype="float32"):
        import jax.numpy as jnp

        return jnp.zeros(shape, dtype=dtype)


class Ones(InitializationMethod):
    def init(self, rng, shape, dtype="float32"):
        import jax.numpy as jnp

        return jnp.ones(shape, dtype=dtype)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float) -> None:
        self.value = value

    def init(self, rng, shape, dtype="float32"):
        import jax.numpy as jnp

        return jnp.full(shape, self.value, dtype=dtype)


class RandomUniform(InitializationMethod):
    """Uniform(lower, upper); no-arg form uses Torch default ±1/sqrt(fan_in)."""

    def __init__(self, lower: float = None, upper: float = None) -> None:
        self.lower = lower
        self.upper = upper

    def init(self, rng, shape, dtype="float32"):
        import jax

        if self.lower is None:
            fan_in, _ = _fans(shape)
            bound = 1.0 / np.sqrt(max(fan_in, 1))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, minval=lo, maxval=hi, dtype=dtype)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0) -> None:
        self.mean = mean
        self.stdv = stdv

    def init(self, rng, shape, dtype="float32"):
        import jax

        return self.mean + self.stdv * jax.random.normal(rng, shape, dtype=dtype)


class Xavier(InitializationMethod):
    """Glorot uniform: ±sqrt(6/(fan_in+fan_out)) — reference ``Xavier``."""

    def init(self, rng, shape, dtype="float32"):
        import jax

        fan_in, fan_out = _fans(shape)
        bound = np.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, minval=-bound, maxval=bound, dtype=dtype)


class MsraFiller(InitializationMethod):
    """He init — reference ``MsraFiller(varianceNormAverage)``; N(0, sqrt(2/fan))."""

    def __init__(self, variance_norm_average: bool = True) -> None:
        self.variance_norm_average = variance_norm_average

    def init(self, rng, shape, dtype="float32"):
        import jax

        fan_in, fan_out = _fans(shape)
        n = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_in
        std = np.sqrt(2.0 / max(n, 1.0))
        return std * jax.random.normal(rng, shape, dtype=dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear-upsampling kernel for deconvolution weights."""

    def init(self, rng, shape, dtype="float32"):
        import jax.numpy as jnp

        if len(shape) < 4:
            raise ValueError("BilinearFiller needs a 4D+ weight")
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = np.ceil(kh / 2.0), np.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        filt = (1 - np.abs(yy / f_h - c_h)) * (1 - np.abs(xx / f_w - c_w))
        w = np.zeros(shape, dtype=np.float32)
        w[..., :, :] = filt
        return jnp.asarray(w, dtype=dtype)
