"""Extended criterion set (completing SURVEY.md §2.2's ~30-criterion row).

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — ``CosineEmbeddingCriterion``, ``HingeEmbeddingCriterion``,
``MarginRankingCriterion``, ``MultiMarginCriterion``,
``MultiLabelMarginCriterion``, ``L1Cost``, ``SoftmaxWithCriterion``,
``DiceCoefficientCriterion``, ``MultiCriterion``, ``KLDCriterion``,
``GaussianCriterion``, ``CosineDistanceCriterion``. Torch-heritage
semantics kept: 1-based class labels, ``size_average`` batch mean, ±1
similarity labels.

Each is one pure scalar ``apply(input, target)`` that jits into the train
step; tensor-pair inputs arrive as 2-element Tables (lists).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from bigdl_tpu.nn.criterion import AbstractCriterion


def _mean_or_sum(x, size_average: bool, n):
    return x / n if size_average else x


class CosineEmbeddingCriterion(AbstractCriterion):
    """Input ``[x1, x2]`` (N, D), target y ∈ {1, -1} per row:
    ``1 - cos`` for similar pairs, ``max(0, cos - margin)`` for dissimilar."""

    def __init__(self, margin: float = 0.0, size_average: bool = True) -> None:
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        from bigdl_tpu.nn.layers_extra import cosine_similarity

        x1, x2 = input
        y = jnp.reshape(jnp.asarray(target), (-1,))
        cos = cosine_similarity(x1, x2)
        per = jnp.where(y > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _mean_or_sum(jnp.sum(per), self.size_average, per.shape[0])


class HingeEmbeddingCriterion(AbstractCriterion):
    """Scalar distances x with y ∈ {1, -1}: ``x`` when similar,
    ``max(0, margin - x)`` when dissimilar."""

    def __init__(self, margin: float = 1.0, size_average: bool = True) -> None:
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        x = jnp.reshape(input, (-1,))
        y = jnp.reshape(jnp.asarray(target), (-1,))
        per = jnp.where(y > 0, x, jnp.maximum(0.0, self.margin - x))
        return _mean_or_sum(jnp.sum(per), self.size_average, per.shape[0])


class MarginRankingCriterion(AbstractCriterion):
    """Input ``[x1, x2]`` scores; y=1 means x1 should rank higher:
    ``max(0, -y(x1 - x2) + margin)``."""

    def __init__(self, margin: float = 0.0, size_average: bool = True) -> None:
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        x1 = jnp.reshape(input[0], (-1,))
        x2 = jnp.reshape(input[1], (-1,))
        y = jnp.reshape(jnp.asarray(target), (-1,))
        per = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return _mean_or_sum(jnp.sum(per), self.size_average, per.shape[0])


class MultiMarginCriterion(AbstractCriterion):
    """Multi-class hinge on (N, C) scores with 1-based targets:
    mean over classes of ``max(0, margin - x[y] + x[i])^p``."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True) -> None:
        super().__init__()
        assert p in (1, 2)
        self.p = p
        self.weights = weights
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        x = input if input.ndim == 2 else input[None]
        t = jnp.reshape(jnp.asarray(target), (-1,)).astype(jnp.int32) - 1
        n, c = x.shape
        xy = jnp.take_along_axis(x, t[:, None], 1)          # (N, 1)
        m = jnp.maximum(0.0, self.margin - xy + x)          # (N, C)
        if self.p == 2:
            m = m * m
        if self.weights is not None:
            m = m * jnp.take(jnp.asarray(self.weights), t)[:, None]
        # the y-th column contributes margin^p; zero it like the reference
        mask = jnp.arange(c)[None, :] != t[:, None]
        per = jnp.sum(m * mask, -1) / c
        return _mean_or_sum(jnp.sum(per), self.size_average, n)


class MultiLabelMarginCriterion(AbstractCriterion):
    """(N, C) scores, targets (N, C): 1-based class indices, 0-padded
    (torch convention). Hinge between every target class and every
    non-target class, normalized by C."""

    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        x = input if input.ndim == 2 else input[None]
        t = jnp.asarray(target).astype(jnp.int32)
        t = t if t.ndim == 2 else t[None]
        n, c = x.shape
        # torch semantics: only indices BEFORE the first 0 are targets
        seen_zero = jnp.cumsum(t == 0, axis=1) > 0
        valid = (t > 0) & (~seen_zero)                      # (N, K)
        tclamped = jnp.maximum(t - 1, 0)
        # is_target[b, c] = class c is one of row b's targets
        is_target = jnp.any(
            (jnp.arange(c)[None, None, :] == tclamped[:, :, None]) & valid[:, :, None],
            axis=1,
        )
        xt = jnp.take_along_axis(x, tclamped, 1)            # (N, K) target scores
        # hinge: for each valid target j and each non-target i
        h = jnp.maximum(0.0, 1.0 - (xt[:, :, None] - x[:, None, :]))  # (N,K,C)
        contrib = h * valid[:, :, None] * (~is_target)[:, None, :]
        per = jnp.sum(contrib, (1, 2)) / c
        return _mean_or_sum(jnp.sum(per), self.size_average, n)


class L1Cost(AbstractCriterion):
    """``sum |input|`` — the target is ignored (reference ``L1Cost``)."""

    def apply(self, input, target=None):
        import jax.numpy as jnp

        return jnp.sum(jnp.abs(input))


class SoftmaxWithCriterion(AbstractCriterion):
    """Caffe-style SoftmaxWithLoss: raw logits (N, C) + 1-based targets;
    softmax and NLL fused (one stable log_softmax under XLA)."""

    _MODES = ("VALID", "FULL", "BATCH_SIZE", "NONE")

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID") -> None:
        super().__init__()
        if normalize_mode not in self._MODES:
            raise ValueError(
                f"normalize_mode must be one of {self._MODES}, "
                f"got {normalize_mode!r}")
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def apply(self, input, target):
        import jax
        import jax.numpy as jnp

        x = input if input.ndim == 2 else input[None]
        t = jnp.reshape(jnp.asarray(target), (-1,)).astype(jnp.int32) - 1
        logp = jax.nn.log_softmax(x, axis=-1)
        picked = jnp.take_along_axis(logp, jnp.maximum(t, 0)[:, None], 1)[:, 0]
        n_valid = picked.shape[0]
        if self.ignore_label is not None:
            keep = t != (self.ignore_label - 1)
            picked = picked * keep
            n_valid = jnp.maximum(jnp.sum(keep), 1)
        if self.normalize_mode == "NONE":
            return -jnp.sum(picked)
        if self.normalize_mode in ("FULL", "BATCH_SIZE"):
            return -jnp.sum(picked) / picked.shape[0]
        return -jnp.sum(picked) / n_valid  # VALID


class DiceCoefficientCriterion(AbstractCriterion):
    """1 - Dice overlap (segmentation loss): ``1 - 2·Σxt / (Σx + Σt + ε)``."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0) -> None:
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def apply(self, input, target):
        import jax.numpy as jnp

        x = input.reshape(input.shape[0], -1)
        t = jnp.asarray(target).reshape(x.shape)
        inter = jnp.sum(x * t, -1)
        per = 1.0 - (2.0 * inter + self.epsilon) / (
            jnp.sum(x, -1) + jnp.sum(t, -1) + self.epsilon)
        return _mean_or_sum(jnp.sum(per), self.size_average, per.shape[0])


class MultiCriterion(AbstractCriterion):
    """Weighted sum of sub-criterions over the SAME (input, target)."""

    def __init__(self) -> None:
        super().__init__()
        self.criterions: List[AbstractCriterion] = []
        self.weights: List[float] = []

    def add(self, criterion: AbstractCriterion, weight: float = 1.0) -> "MultiCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def apply(self, input, target):
        total = 0.0
        for c, w in zip(self.criterions, self.weights):
            total = total + w * c.apply(input, target)
        return total


class KLDCriterion(AbstractCriterion):
    """VAE posterior KL to N(0, I): input ``[mean, log_var]``, target
    ignored: ``-½ Σ (1 + log σ² - μ² - σ²)`` averaged over the batch."""

    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target=None):
        import jax.numpy as jnp

        mean, log_var = input
        kl = -0.5 * jnp.sum(1.0 + log_var - mean * mean - jnp.exp(log_var))
        return _mean_or_sum(kl, self.size_average, mean.shape[0])


class GaussianCriterion(AbstractCriterion):
    """Negative log-likelihood of the target under N(mean, σ²) with input
    ``[mean, log_var]``: ``½ Σ (log 2π + log σ² + (t-μ)²/σ²)``."""

    def apply(self, input, target):
        import jax.numpy as jnp

        mean, log_var = input
        t = jnp.asarray(target)
        return 0.5 * jnp.sum(
            jnp.log(2.0 * jnp.pi) + log_var
            + (t - mean) ** 2 / jnp.exp(log_var)
        )


class CosineDistanceCriterion(AbstractCriterion):
    """``1 - cos(input, target)`` per row (reference
    ``CosineDistanceCriterion``)."""

    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        from bigdl_tpu.nn.layers_extra import cosine_similarity

        t = jnp.asarray(target)
        per = 1.0 - cosine_similarity(input, t)
        return _mean_or_sum(jnp.sum(per), self.size_average, per.shape[0])


class SoftMarginCriterion(AbstractCriterion):
    """Two-class logistic loss over ±1 targets:
    ``mean(log(1 + exp(-y·x)))`` (reference ``SoftMarginCriterion``)."""

    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax
        import jax.numpy as jnp

        t = jnp.asarray(target)
        # log(1 + exp(-z)) == -log_sigmoid(z), stable for large |z|
        per = -jax.nn.log_sigmoid(t * input)
        return _mean_or_sum(jnp.sum(per), self.size_average, per.size)


class CosineProximityCriterion(AbstractCriterion):
    """``-mean(cos(input, target))`` (reference keras-era
    ``CosineProximityCriterion``)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        from bigdl_tpu.nn.layers_extra import cosine_similarity

        return -jnp.mean(cosine_similarity(input, jnp.asarray(target)))


class ClassSimplexCriterion(AbstractCriterion):
    """MSE against simplex-embedded class targets (reference
    ``ClassSimplexCriterion``): each class maps to a vertex of a regular
    (nClasses-1)-simplex; the loss is the squared distance to the target
    vertex."""

    def __init__(self, n_classes: int, size_average: bool = True) -> None:
        super().__init__()
        assert n_classes > 1
        self.n_classes = n_classes
        self.size_average = size_average
        self._simplex = self._build_simplex(n_classes)

    @staticmethod
    def _build_simplex(n: int) -> np.ndarray:
        # closed form: identity minus centroid, row-normalized — n unit
        # vectors with equal pairwise angles (a regular simplex in R^n)
        eye = np.eye(n, dtype=np.float32)
        v = eye - eye.mean(axis=0, keepdims=True)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        return v

    def apply(self, input, target):
        import jax.numpy as jnp

        t = jnp.asarray(target).astype(jnp.int32).reshape(-1) - 1
        tv = jnp.asarray(self._simplex)[t]          # (N, n_classes)
        diff = input - tv
        loss = jnp.sum(diff * diff)
        return _mean_or_sum(loss, self.size_average, input.size)
