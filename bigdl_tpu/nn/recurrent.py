"""Recurrent family — cells, unrollers, bidirectional wrapper.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/Recurrent.scala`` (time
loop + hidden-state management), ``Cell.scala``, ``LSTM.scala``,
``LSTMPeephole.scala``, ``GRU.scala``, ``RnnCell.scala``,
``BiRecurrent.scala``, ``RecurrentDecoder.scala``, ``TimeDistributed.scala``.

TPU-native redesign: the reference unrolls time in a serial Scala loop over
mutable hidden tensors (SURVEY.md §5.7) — one layer call per step, no fusion
across steps. Here the whole sequence is ONE ``jax.lax.scan``: XLA compiles
the per-step cell body once, keeps the carry in registers/VMEM, and the
input/output time axes are laid out as a single HBM array. Gate projections
for the input leg are batched over ALL timesteps in one big gemm before the
scan (``x @ W_ih^T`` on the full (B,T,I) array — MXU-friendly), so the scan
body only carries the hidden-to-hidden gemm.

Layout: activity is ``(batch, time, feature)`` (reference ``batchFirst``
convention for ``Recurrent``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.nn.module import AbstractModule, TensorModule


class Cell(AbstractModule):
    """Base of recurrent cells.

    Pure single-step contract: ``step(params, x_t, carry) -> (out_t, carry)``
    with ``init_carry(batch_size)`` building the zero carry. A cell can also
    be driven through the generic ``apply`` facade, where the input is a list
    ``[x_t, *carry]`` and the output ``[out_t, *carry]`` (the reference's
    ``T(input, hidden)`` table convention).
    """

    # regularizer key sets consumed by optim.train_step's walkers
    _reg_w_keys = ("w_ih",)
    _reg_u_keys = ("w_hh",)
    _reg_b_keys = ("b_ih", "b_hh")

    def __init__(self, hidden_size: int) -> None:
        super().__init__()
        self.hidden_size = hidden_size
        self.p = 0.0  # dropout probability (see Recurrent.apply)

    # number of carry tensors (1 for RNN/GRU, 2 for LSTM)
    carry_len = 1

    def init_carry(self, batch_size: int):
        import jax.numpy as jnp

        return tuple(
            jnp.zeros((batch_size, self.hidden_size), jnp.float32)
            for _ in range(self.carry_len)
        )

    def init_carry_for(self, x):
        """Zero carry shaped for sequence input ``x`` (B, T, ...). Default
        delegates to :meth:`init_carry`; spatial cells (ConvLSTM) override
        to size the state from x's spatial dims."""
        return self.init_carry(x.shape[0])

    @property
    def input_dropout_p(self) -> float:
        """Dropout applied to the sequence INPUT by the driving Recurrent."""
        return self.p

    def dropout_specs(self):
        """Variational h-dropout specs ``[(p, hidden_size), ...]`` — one per
        recurrent sub-unit; the driving ``Recurrent`` samples one mask per
        spec per sequence and hands them to :meth:`mask_carry`."""
        return [(self.p, self.hidden_size)]

    def mask_carry(self, carry, h_masks):
        """Apply per-sequence recurrent-leg masks (aligned with
        :meth:`dropout_specs`) to the hidden state(s)."""
        m = h_masks[0]
        if m is None:
            return carry
        return (carry[0] * m,) + tuple(carry[1:])

    def with_masks(self, h_masks):
        """Return the step function with extra per-sequence dropout masks
        bound (beyond what :meth:`mask_carry` applies). Plain cells have
        none; ``MultiRNNCell`` binds its inter-layer input masks here."""
        return self.step_pre

    def step(self, params, x_t, carry):
        raise NotImplementedError

    def precompute_input(self, params, x):
        """Optional whole-sequence input projection done OUTSIDE the scan.

        Returns an array consumed by ``step_pre`` instead of the raw
        ``x_t``. Default: identity (no precompute).
        """
        return x

    def step_pre(self, params, pre_t, carry):
        """Step consuming a precomputed input slice (default: raw step)."""
        return self.step(params, pre_t, carry)

    def apply(self, params, input, state=None, training=False, rng=None):
        x_t, carry = input[0], tuple(input[1:])
        if not carry:
            carry = self.init_carry_for(x_t)
        out, new_carry = self.step(params, x_t, carry)
        return [out, *new_carry], state


class _FusedInputCell(Cell):
    """Cells whose input leg is one fused gate projection ``x @ w_ih^T + b_ih``
    — hoisted over the whole sequence (one MXU gemm) by ``Recurrent``."""

    def precompute_input(self, params, x):
        import jax.numpy as jnp

        return jnp.matmul(x, params["w_ih"].T) + params["b_ih"]

    def step(self, params, x_t, carry):
        return self.step_pre(params, self.precompute_input(params, x_t), carry)


class RnnCell(_FusedInputCell):
    """Vanilla RNN: h' = act(W_ih x + b_ih + W_hh h + b_hh)
    (reference ``nn/RnnCell.scala``; both biases kept for torch parity)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation: Optional[AbstractModule] = None,
                 w_regularizer=None, u_regularizer=None,
                 b_regularizer=None) -> None:
        super().__init__(hidden_size)
        from bigdl_tpu.nn.activations import Tanh

        self.input_size = input_size
        self.activation = activation or Tanh()
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def init_params(self, rng):
        import jax

        k = jax.random.split(rng, 4)
        u = RandomUniform()
        return {
            "w_ih": u.init(k[0], (self.hidden_size, self.input_size)),
            "w_hh": u.init(k[1], (self.hidden_size, self.hidden_size)),
            "b_ih": Zeros().init(k[2], (self.hidden_size,)),
            "b_hh": Zeros().init(k[3], (self.hidden_size,)),
        }

    def step_pre(self, params, pre_t, carry):
        import jax.numpy as jnp

        (h,) = carry
        a = pre_t + jnp.matmul(h, params["w_hh"].T) + params["b_hh"]
        out, _ = self.activation.apply({}, a, {}, training=False, rng=None)
        return out, (out,)


class LSTM(_FusedInputCell):
    """LSTM cell (reference ``nn/LSTM.scala``). Gate order i, f, g, o in the
    fused weight matrices (torch layout, for oracle parity tests)."""

    carry_len = 2  # (h, c)

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None) -> None:
        super().__init__(hidden_size)
        self.input_size = input_size
        self.p = p
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def init_params(self, rng):
        import jax

        k = jax.random.split(rng, 4)
        u = RandomUniform()
        H, I = self.hidden_size, self.input_size
        return {
            "w_ih": u.init(k[0], (4 * H, I)),
            "w_hh": u.init(k[1], (4 * H, H)),
            "b_ih": Zeros().init(k[2], (4 * H,)),
            "b_hh": Zeros().init(k[3], (4 * H,)),
        }

    def step_pre(self, params, pre_t, carry):
        import jax
        import jax.numpy as jnp

        h, c = carry
        gates = pre_t + jnp.matmul(h, params["w_hh"].T) + params["b_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)


class LSTMPeephole(LSTM):
    """LSTM with peephole connections from the cell state into the i/f/o
    gates (reference ``nn/LSTMPeephole.scala``; diagonal peephole weights)."""

    def init_params(self, rng):
        import jax

        p = super().init_params(rng)
        # fresh stream: split(rng, 3) would repeat the first 3 of the
        # split(rng, 4) the base class already consumed
        k = jax.random.split(jax.random.fold_in(rng, 1), 3)
        u = RandomUniform()
        H = self.hidden_size
        p["w_pi"] = u.init(k[0], (H,))
        p["w_pf"] = u.init(k[1], (H,))
        p["w_po"] = u.init(k[2], (H,))
        return p

    def step_pre(self, params, pre_t, carry):
        import jax
        import jax.numpy as jnp

        h, c = carry
        gates = pre_t + jnp.matmul(h, params["w_hh"].T) + params["b_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i + params["w_pi"] * c)
        f = jax.nn.sigmoid(f + params["w_pf"] * c)
        g = jnp.tanh(g)
        new_c = f * c + i * g
        o = jax.nn.sigmoid(o + params["w_po"] * new_c)
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with per-channel peepholes over (B, C, H, W)
    frames (reference ``nn/ConvLSTMPeephole.scala`` — the precipitation-
    nowcasting ConvLSTM). Gates are SAME-padded convolutions of the input
    frame and the hidden state; state (h, c) is (B, n_output, H, W).

    Drive with ``Recurrent`` over (B, T, C, H, W) sequences; the input-leg
    conv of ALL four gates is hoisted over the whole sequence as one
    batched conv (the conv analog of the fused-gemm ``_FusedInputCell``)."""

    carry_len = 2  # (h, c)

    def __init__(self, input_size: int, output_size: int,
                 kernel_i: int = 3, kernel_c: int = 3,
                 stride: int = 1, p: float = 0.0,
                 with_peephole: bool = True,
                 w_regularizer=None, u_regularizer=None,
                 b_regularizer=None) -> None:
        super().__init__(output_size)
        if stride != 1:
            raise ValueError("ConvLSTMPeephole: state recurrence needs "
                             "stride 1 (reference contract)")
        self.input_size = input_size
        self.output_size = output_size
        self.kernel_i = kernel_i     # input-to-gate kernel
        self.kernel_c = kernel_c     # hidden-to-gate kernel
        self.p = p
        self.with_peephole = with_peephole
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def init_params(self, rng):
        import jax

        k = jax.random.split(rng, 3)
        u = RandomUniform()
        O, I = self.output_size, self.input_size
        p = {
            "w_ih": u.init(k[0], (4 * O, I, self.kernel_i, self.kernel_i)),
            "w_hh": u.init(k[1], (4 * O, O, self.kernel_c, self.kernel_c)),
            "b_ih": Zeros().init(k[2], (4 * O,)),
        }
        if self.with_peephole:
            kp = jax.random.split(jax.random.fold_in(rng, 1), 3)
            for name, key in zip(("w_pi", "w_pf", "w_po"), kp):
                p[name] = u.init(key, (O, 1, 1))  # per-channel peephole
        return p

    def _conv(self, x, w, b=None):
        import jax.lax as lax

        out = lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    def init_carry_for(self, x):
        import jax.numpy as jnp

        spatial = x.shape[-2:]
        return tuple(
            jnp.zeros((x.shape[0], self.output_size) + spatial, jnp.float32)
            for _ in range(self.carry_len))

    def init_carry(self, batch_size: int):
        raise ValueError(
            "ConvLSTMPeephole state needs the frame's spatial dims — drive "
            "it through Recurrent (which uses init_carry_for)")

    def dropout_specs(self):
        # variational masks are per-(batch, channel); broadcast over H, W
        return [(self.p, self.output_size)]

    def mask_carry(self, carry, h_masks):
        m = h_masks[0]
        if m is None:
            return carry
        return (carry[0] * m[:, :, None, None],) + tuple(carry[1:])

    def precompute_input(self, params, x):
        """(B, T, C, H, W): fold T into the batch for ONE gate conv."""
        b, t = x.shape[:2]
        flat = x.reshape((b * t,) + x.shape[2:])
        pre = self._conv(flat, params["w_ih"], params["b_ih"])
        return pre.reshape((b, t) + pre.shape[1:])

    def step_pre(self, params, pre_t, carry):
        import jax
        import jax.numpy as jnp

        h, c = carry
        gates = pre_t + self._conv(h, params["w_hh"])
        i, f, g, o = jnp.split(gates, 4, axis=1)     # channel axis
        if self.with_peephole:
            i = i + params["w_pi"][None] * c
            f = f + params["w_pf"][None] * c
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        new_c = f * c + i * g
        if self.with_peephole:
            o = o + params["w_po"][None] * new_c
        o = jax.nn.sigmoid(o)
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)

    def step(self, params, x_t, carry):
        return self.step_pre(
            params, self._conv(x_t, params["w_ih"], params["b_ih"]), carry)


class GRU(_FusedInputCell):
    """GRU cell (reference ``nn/GRU.scala``). Gate order r, z, n; separate
    input/hidden biases so the candidate gate matches torch:
    n = tanh(W_in x + b_in + r * (W_hn h + b_hn)).

    ``reset_after=False`` selects the keras-1 convention instead — the
    reset gate is applied to the hidden state BEFORE the candidate's
    recurrent matmul, n = tanh(W_in x + b_in + W_hn (r * h) + b_hn) —
    which is what ``Model.load_keras`` GRU weights were trained under
    (the two formulations are not weight-convertible into each other)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None,
                 reset_after: bool = True) -> None:
        super().__init__(hidden_size)
        self.input_size = input_size
        self.p = p
        self.reset_after = reset_after
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def init_params(self, rng):
        import jax

        k = jax.random.split(rng, 4)
        u = RandomUniform()
        H, I = self.hidden_size, self.input_size
        return {
            "w_ih": u.init(k[0], (3 * H, I)),
            "w_hh": u.init(k[1], (3 * H, H)),
            "b_ih": Zeros().init(k[2], (3 * H,)),
            "b_hh": Zeros().init(k[3], (3 * H,)),
        }

    def step_pre(self, params, pre_t, carry):
        import jax
        import jax.numpy as jnp

        (h,) = carry
        xr, xz, xn = jnp.split(pre_t, 3, axis=-1)
        if getattr(self, "reset_after", True):
            hp = jnp.matmul(h, params["w_hh"].T) + params["b_hh"]
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
        else:
            # keras1 convention: reset gate gates the STATE, then the
            # candidate matmul runs on the gated state — W_hn cannot be
            # hoisted out of r, so the r/z half and the n half split
            H = self.hidden_size
            hp = jnp.matmul(h, params["w_hh"][:2 * H].T) \
                + params["b_hh"][:2 * H]
            hr, hz = jnp.split(hp, 2, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + jnp.matmul(r * h, params["w_hh"][2 * H:].T)
                         + params["b_hh"][2 * H:])
        new_h = (1.0 - z) * n + z * h
        return new_h, (new_h,)


class Recurrent(AbstractModule):
    """Unrolls a cell over the time axis of a ``(batch, time, feature)``
    input (reference ``nn/Recurrent.scala``); output ``(batch, time, hidden)``.

    The serial reference loop becomes one ``lax.scan``; the input-side gate
    gemm runs over the whole sequence before the scan (one MXU matmul).
    """

    def __init__(self) -> None:
        super().__init__()
        self.cell: Optional[Cell] = None
        self.reverse = False

    def add(self, cell: Cell) -> "Recurrent":
        self.cell = cell
        return self

    def sub_modules(self) -> List[AbstractModule]:
        return [self.cell] if self.cell is not None else []

    def _key(self) -> str:
        return f"0:{self.cell.name}"

    def init_params(self, rng):
        return {self._key(): self.cell.init_params(rng)}

    def init_state(self):
        return {self._key(): self.cell.init_state()}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        cell, cp = self.cell, params[self._key()]
        batch = input.shape[0]
        x = input
        h_masks = None
        p_in = getattr(cell, "input_dropout_p", getattr(cell, "p", 0.0))
        specs = cell.dropout_specs()
        if training and rng is not None and (
                p_in > 0.0 or any(p_h > 0.0 for p_h, _ in specs)):
            # variational dropout (one mask per sequence, shared across
            # timesteps) on the input and on each recurrent h connection —
            # the role of the reference cells' dropout `p`; a stacked
            # MultiRNNCell contributes one spec (and one mask) per sub-cell
            ks = jax.random.split(rng, len(specs) + 1)
            if p_in > 0.0:
                keep = 1.0 - p_in
                in_mask = jax.random.bernoulli(
                    ks[0], keep, (batch, 1) + x.shape[2:]
                ).astype(x.dtype) / keep
                x = x * in_mask
            masks = []
            for k_h, (p_h, h_sz) in zip(ks[1:], specs):
                if p_h > 0.0:
                    keep = 1.0 - p_h
                    masks.append(jax.random.bernoulli(
                        k_h, keep, (batch, h_sz)).astype(x.dtype) / keep)
                else:
                    masks.append(None)
            if any(m is not None for m in masks):
                h_masks = masks
        pre = cell.precompute_input(cp, x)           # (B, T, ...)
        pre_t = jnp.swapaxes(pre, 0, 1)              # (T, B, ...)
        carry0 = cell.init_carry_for(x)

        stepf = cell.with_masks(h_masks) if h_masks is not None else cell.step_pre

        def body(carry, p_t):
            if h_masks is not None:
                carry = cell.mask_carry(carry, h_masks)
            out, new_carry = stepf(cp, p_t, carry)
            return new_carry, out

        # reverse mode scans from the last timestep; lax.scan stacks each
        # step's output at its original position, which IS the reversed-RNN
        # output layout (no explicit flips needed)
        _, outs = jax.lax.scan(body, carry0, pre_t, reverse=self.reverse)
        out = jnp.swapaxes(outs, 0, 1)               # (B, T, H)
        return out, state


class BiRecurrent(AbstractModule):
    """Forward + time-reversed ``Recurrent`` merged per step (reference
    ``nn/BiRecurrent.scala``; default merge = elementwise add, the
    reference's ``CAddTable``; ``merge_mode="concat"`` = ``JoinTable``)."""

    def __init__(self, merge: Optional[str] = None) -> None:
        super().__init__()
        self.merge_mode = merge or "add"
        if self.merge_mode not in ("add", "concat"):
            raise ValueError(f"unknown merge {merge!r}")
        self.fwd = Recurrent()
        self.bwd = Recurrent()
        self.bwd.reverse = True

    def add(self, cell: Cell) -> "BiRecurrent":
        import copy

        self.fwd.add(cell)
        bwd_cell = copy.deepcopy(cell)
        bwd_cell.name = cell.name + "_rev"
        self.bwd.add(bwd_cell)
        return self

    def sub_modules(self) -> List[AbstractModule]:
        return [self.fwd, self.bwd]

    def init_params(self, rng):
        import jax

        return {
            f"0:{self.fwd.name}": self.fwd.init_params(jax.random.fold_in(rng, 0)),
            f"1:{self.bwd.name}": self.bwd.init_params(jax.random.fold_in(rng, 1)),
        }

    def init_state(self):
        return {
            f"0:{self.fwd.name}": self.fwd.init_state(),
            f"1:{self.bwd.name}": self.bwd.init_state(),
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        state = state or {}
        kf, kb = (None, None)
        if rng is not None:
            import jax

            kf, kb = jax.random.split(rng)
        fk, bk = f"0:{self.fwd.name}", f"1:{self.bwd.name}"
        fo, fs = self.fwd.apply(params[fk], input, state.get(fk, {}),
                                training=training, rng=kf)
        bo, bs = self.bwd.apply(params[bk], input, state.get(bk, {}),
                                training=training, rng=kb)
        if self.merge_mode == "add":
            out = fo + bo
        else:
            out = jnp.concatenate([fo, bo], axis=-1)
        return out, {fk: fs, bk: bs}


class RecurrentDecoder(AbstractModule):
    """Decoder unroll (reference ``nn/RecurrentDecoder.scala``): the input is
    the FIRST timestep ``(batch, feature)``; each step's output feeds the
    next step's input, for ``output_length`` steps. Requires a cell whose
    output size equals its input size."""

    def __init__(self, output_length: int) -> None:
        super().__init__()
        self.output_length = output_length
        self.cell: Optional[Cell] = None

    def add(self, cell: Cell) -> "RecurrentDecoder":
        self.cell = cell
        return self

    def sub_modules(self) -> List[AbstractModule]:
        return [self.cell] if self.cell is not None else []

    def _key(self) -> str:
        return f"0:{self.cell.name}"

    def init_params(self, rng):
        return {self._key(): self.cell.init_params(rng)}

    def init_state(self):
        return {self._key(): self.cell.init_state()}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        cell, cp = self.cell, params[self._key()]
        carry0 = cell.init_carry_for(input)

        def body(loop_carry, _):
            x_t, carry = loop_carry
            out, new_carry = cell.step(cp, x_t, carry)
            return (out, new_carry), out

        _, outs = jax.lax.scan(
            body, (input, carry0), None, length=self.output_length
        )
        return jnp.swapaxes(outs, 0, 1), state


class TimeDistributed(TensorModule):
    """Applies an inner layer independently to every timestep of a
    ``(batch, time, ...)`` activity (reference ``nn/TimeDistributed.scala``):
    fold time into batch, run the layer ONCE on the (B·T, ...) array — a
    single big MXU-friendly call instead of T small ones — and unfold."""

    def __init__(self, layer: AbstractModule) -> None:
        super().__init__()
        self.layer = layer

    def sub_modules(self) -> List[AbstractModule]:
        return [self.layer]

    def _key(self) -> str:
        return f"0:{self.layer.name}"

    def init_params(self, rng):
        return {self._key(): self.layer.init_params(rng)}

    def init_state(self):
        return {self._key(): self.layer.init_state()}

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        b, t = input.shape[0], input.shape[1]
        flat = input.reshape((b * t,) + input.shape[2:])
        out, s = self.layer.apply(
            params[self._key()], flat, state.get(self._key(), {}),
            training=training, rng=rng,
        )
        out = out.reshape((b, t) + out.shape[1:])
        return out, {self._key(): s}


class MultiRNNCell(Cell):
    """Stack of cells run as ONE cell (reference ``nn/MultiRNNCell.scala``):
    each sub-cell's output feeds the next; the combined carry is the
    concatenation of all sub-carries, so the whole stack unrolls inside a
    single ``lax.scan`` (one fused compiled loop instead of nested ones)."""

    def __init__(self, cells: List[Cell]) -> None:
        super().__init__(cells[-1].hidden_size)
        self.cells = list(cells)
        self.carry_len = sum(c.carry_len for c in self.cells)

    def sub_modules(self) -> List[AbstractModule]:
        return list(self.cells)

    def _key(self, i: int, c: Cell) -> str:
        return f"{i}:{c.name}"

    def init_params(self, rng):
        import jax

        keys = jax.random.split(rng, len(self.cells))
        return {
            self._key(i, c): c.init_params(k)
            for i, (c, k) in enumerate(zip(self.cells, keys))
        }

    def init_carry(self, batch_size: int):
        out = []
        for c in self.cells:
            out.extend(c.init_carry(batch_size))
        return tuple(out)

    def init_carry_for(self, x):
        # spatial cells (ConvLSTM) size their state from x's spatial dims,
        # which stride-1 stacks preserve layer to layer
        out = []
        for c in self.cells:
            out.extend(c.init_carry_for(x))
        return tuple(out)

    @property
    def input_dropout_p(self) -> float:
        # the sequence input feeds the FIRST sub-cell
        return self.cells[0].p

    def _n_h_specs(self) -> int:
        return sum(len(c.dropout_specs()) for c in self.cells)

    def dropout_specs(self):
        # recurrent-leg specs per sub-cell, then inter-layer INPUT specs:
        # sub-cell i>0's p also drops its input connection (the previous
        # cell's per-step output, sized to that cell's hidden) — matching
        # the reference cells whose p drops the w_ih leg
        out = []
        for c in self.cells:
            out.extend(c.dropout_specs())
        for i in range(1, len(self.cells)):
            out.append((self.cells[i].p, self.cells[i - 1].hidden_size))
        return out

    def mask_carry(self, carry, h_masks):
        new = list(carry)
        idx = 0
        mi = 0
        for c in self.cells:
            sub = tuple(new[idx: idx + c.carry_len])
            n = len(c.dropout_specs())
            sub = c.mask_carry(sub, h_masks[mi: mi + n])
            new[idx: idx + c.carry_len] = list(sub)
            idx += c.carry_len
            mi += n
        return tuple(new)

    def with_masks(self, h_masks):
        in_masks = h_masks[self._n_h_specs():]

        def stepf(params, pre_t, carry):
            return self._run_stack(params, pre_t, carry, in_masks)

        return stepf

    def precompute_input(self, params, x):
        # hoist the FIRST sub-cell's fused input gemm over the whole
        # sequence (one MXU matmul outside the scan); later sub-cells
        # consume the previous cell's per-step output, so they step inside
        c0 = self.cells[0]
        return c0.precompute_input(params[self._key(0, c0)], x)

    def step_pre(self, params, pre_t, carry):
        return self._run_stack(params, pre_t, carry, None)

    def _run_stack(self, params, pre_t, carry, in_masks):
        new = []
        h = pre_t
        idx = 0
        for i, c in enumerate(self.cells):
            sub = carry[idx: idx + c.carry_len]
            idx += c.carry_len
            if i == 0:
                h, nc = c.step_pre(params[self._key(0, c)], h, tuple(sub))
            else:
                if in_masks is not None and in_masks[i - 1] is not None:
                    h = h * in_masks[i - 1]
                h, nc = c.step(params[self._key(i, c)], h, tuple(sub))
            new.extend(nc)
        return h, tuple(new)

    def step(self, params, x_t, carry):
        c0 = self.cells[0]
        pre = c0.precompute_input(params[self._key(0, c0)], x_t)
        return self.step_pre(params, pre, carry)
