"""Activation layers.

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — ``ReLU`` (optionally in-place), ``Tanh``, ``Sigmoid``,
``SoftMax``, ``LogSoftMax``, ``PReLU``, ``ELU``, ``HardTanh``, ``LeakyReLU``,
``SoftPlus``, ``SoftSign``.

TPU-native: pure elementwise jnp ops; XLA fuses them into the surrounding
matmul/conv so "in-place" (a memory-traffic optimization on the JVM heap)
has no meaning here — the flag is accepted and ignored.
"""

from __future__ import annotations

from bigdl_tpu.nn.module import TensorModule


class ReLU(TensorModule):
    def __init__(self, ip: bool = False) -> None:
        super().__init__()
        self.inplace = ip  # accepted for parity; fusion makes it moot

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.maximum(input, 0.0), state


class ReLU6(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.clip(input, 0.0, 6.0), state


class Tanh(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.tanh(input), state


class Sigmoid(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.sigmoid(input), state


class SoftMax(TensorModule):
    """Softmax over the feature dim (last for 1/2-D, channel for 3/4-D)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        axis = -1 if input.ndim <= 2 else 1
        return jax.nn.softmax(input, axis=axis), state


class LogSoftMax(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.log_softmax(input, axis=-1), state


class PReLU(TensorModule):
    def __init__(self, n_output_plane: int = 0) -> None:
        super().__init__()
        self.n_output_plane = n_output_plane  # 0 = single shared alpha

    def init_params(self, rng):
        import jax.numpy as jnp

        n = self.n_output_plane if self.n_output_plane > 0 else 1
        return {"weight": jnp.full((n,), 0.25)}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        w = params["weight"]
        if self.n_output_plane > 0 and input.ndim >= 3:
            w = w[None, :, None, None] if input.ndim == 4 else w[:, None, None]
        elif self.n_output_plane > 0 and input.ndim == 2:
            w = w[None, :]
        return jnp.where(input > 0, input, w * input), state


class ELU(TensorModule):
    def __init__(self, alpha: float = 1.0, inplace: bool = False) -> None:
        super().__init__()
        self.alpha = alpha

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.where(input > 0, input, self.alpha * (jnp.exp(input) - 1.0)), state


class LeakyReLU(TensorModule):
    def __init__(self, negval: float = 0.01, inplace: bool = False) -> None:
        super().__init__()
        self.negval = negval

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.where(input > 0, input, self.negval * input), state


class HardTanh(TensorModule):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False) -> None:
        super().__init__()
        self.min_value = min_value
        self.max_value = max_value

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.clip(input, self.min_value, self.max_value), state


class SoftPlus(TensorModule):
    def __init__(self, beta: float = 1.0) -> None:
        super().__init__()
        self.beta = beta

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.softplus(self.beta * input) / self.beta, state


class SoftSign(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return input / (1.0 + jnp.abs(input)), state


class GELU(TensorModule):
    """Not in the 0.x reference; provided for the transformer extension path."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.gelu(input), state
