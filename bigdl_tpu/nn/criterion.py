"""Criterions (loss functions).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/abstractnn/AbstractCriterion.scala``
plus one class per criterion file — ``ClassNLLCriterion``,
``CrossEntropyCriterion``, ``MSECriterion``, ``AbsCriterion``,
``BCECriterion``, ``SmoothL1Criterion``, ``MultiLabelSoftMarginCriterion``,
``ParallelCriterion``, ``TimeDistributedCriterion``.

Conventions kept for parity: **class labels are 1-based floats** (the Torch
heritage the reference keeps); ``size_average=True`` divides by batch size.

TPU-native: a criterion is one pure scalar function ``apply(input, target)``;
the facade ``forward``/``backward`` mirrors the reference contract, with
``backward`` = ``jax.grad`` w.r.t. the input. Optimizers jit
``criterion.apply`` straight into the train step.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np


def _unwrap(x):
    from bigdl_tpu.nn.module import _unwrap_activity

    return _unwrap_activity(x)


class AbstractCriterion:
    def __init__(self) -> None:
        self.output: float = 0.0
        self.grad_input: Any = None

    def apply(self, input, target):
        """Pure scalar loss."""
        raise NotImplementedError

    def forward(self, input, target) -> float:
        out = self.apply(_unwrap(input), _unwrap(target))
        self.output = float(out)
        return self.output

    __call__ = forward

    def backward(self, input, target):
        import jax

        x = _unwrap(input)
        t = _unwrap(target)
        self.grad_input = jax.grad(lambda i: self.apply(i, t))(x)
        return self.grad_input

    # reference aliases
    def update_output(self, input, target) -> float:
        return self.forward(input, target)

    def update_grad_input(self, input, target):
        return self.backward(input, target)


class ClassNLLCriterion(AbstractCriterion):
    """Negative log-likelihood over log-probability input (N, C) with 1-based
    integer class targets (N,). ``logProbAsInput=False`` applies log first."""

    def __init__(self, weights=None, size_average: bool = True,
                 log_prob_as_input: bool = True) -> None:
        super().__init__()
        self.weights = weights
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input

    def apply(self, input, target):
        import jax.numpy as jnp

        logp = input if self.log_prob_as_input else jnp.log(input + 1e-8)
        if logp.ndim == 1:
            logp = logp[None]
            target = jnp.reshape(target, (1,))
        idx = jnp.asarray(target).astype(jnp.int32).reshape(-1) - 1
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(jnp.asarray(self.weights), idx)
            loss = -jnp.sum(picked * w)
            return loss / jnp.sum(w) if self.size_average else loss
        loss = -jnp.sum(picked)
        return loss / picked.shape[0] if self.size_average else loss


class CrossEntropyCriterion(AbstractCriterion):
    """LogSoftMax + ClassNLL fused (reference ``CrossEntropyCriterion.scala``).
    Fusing here also gives the numerically-stable logsumexp form."""

    def __init__(self, weights=None, size_average: bool = True) -> None:
        super().__init__()
        self.weights = weights
        self.size_average = size_average

    def apply(self, input, target):
        import jax

        logp = jax.nn.log_softmax(input, axis=-1)
        return ClassNLLCriterion(self.weights, self.size_average).apply(logp, target)


class MSECriterion(AbstractCriterion):
    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        se = jnp.sum((input - target) ** 2)
        return se / input.size if self.size_average else se


class AbsCriterion(AbstractCriterion):
    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        ae = jnp.sum(jnp.abs(input - target))
        return ae / input.size if self.size_average else ae


class BCECriterion(AbstractCriterion):
    def __init__(self, weights=None, size_average: bool = True) -> None:
        super().__init__()
        self.weights = weights
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        eps = 1e-12
        x = jnp.clip(input, eps, 1.0 - eps)
        ll = target * jnp.log(x) + (1.0 - target) * jnp.log(1.0 - x)
        if self.weights is not None:
            ll = ll * jnp.asarray(self.weights)
        loss = -jnp.sum(ll)
        return loss / input.size if self.size_average else loss


class SmoothL1Criterion(AbstractCriterion):
    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        d = jnp.abs(input - target)
        loss = jnp.sum(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5))
        return loss / input.size if self.size_average else loss


class MultiLabelSoftMarginCriterion(AbstractCriterion):
    def __init__(self, weights=None, size_average: bool = True) -> None:
        super().__init__()
        self.weights = weights
        self.size_average = size_average

    def apply(self, input, target):
        import jax

        import jax.numpy as jnp

        logsig = jax.nn.log_sigmoid(input)
        logsig_neg = jax.nn.log_sigmoid(-input)
        ll = target * logsig + (1.0 - target) * logsig_neg
        if self.weights is not None:
            ll = ll * jnp.asarray(self.weights)
        n = input.shape[0] if input.ndim > 1 else 1
        c = input.shape[-1]
        loss = -jnp.sum(ll) / c
        return loss / n if self.size_average else loss


class ParallelCriterion(AbstractCriterion):
    """Weighted sum of criterions over a table of (input, target) pairs
    (reference ``ParallelCriterion.scala``)."""

    def __init__(self, repeat_target: bool = False) -> None:
        super().__init__()
        self.criterions: List[AbstractCriterion] = []
        self.crit_weights: List[float] = []
        self.repeat_target = repeat_target

    def add(self, criterion: AbstractCriterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criterions.append(criterion)
        self.crit_weights.append(weight)
        return self

    def apply(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.crit_weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.apply(input[i], t)
        return total


class TimeDistributedCriterion(AbstractCriterion):
    """Apply a criterion at every time step of (N, T, ...) input
    (reference ``TimeDistributedCriterion.scala``)."""

    def __init__(self, critrn: AbstractCriterion, size_average: bool = False,
                 dimension: int = 2) -> None:
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average
        self.dimension = dimension

    def apply(self, input, target):
        import jax
        import jax.numpy as jnp

        ax = self.dimension - 1
        steps = input.shape[ax]
        # vmap over the time axis — ONE traced criterion instead of a
        # steps-times unrolled Python loop (at T=2048 the unroll dominated
        # trace/compile time)
        xs = jnp.moveaxis(input, ax, 0)
        # the target is per-step when it carries the time axis (same length
        # at ``ax``); otherwise one shared target for every step
        if target.ndim > ax and target.shape[ax] == steps:
            ts = jnp.moveaxis(target, ax, 0)
            per = jax.vmap(self.critrn.apply)(xs, ts)
        else:
            per = jax.vmap(lambda x: self.critrn.apply(x, target))(xs)
        total = jnp.sum(per)
        return total / steps if self.size_average else total


class MarginCriterion(AbstractCriterion):
    """Hinge loss (reference ``MarginCriterion.scala``); targets ±1."""

    def __init__(self, margin: float = 1.0, size_average: bool = True) -> None:
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        loss = jnp.sum(jnp.maximum(0.0, self.margin - input * target))
        return loss / input.size if self.size_average else loss


class DistKLDivCriterion(AbstractCriterion):
    """KL divergence with log-prob input (reference ``DistKLDivCriterion.scala``)."""

    def __init__(self, size_average: bool = True) -> None:
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        import jax.numpy as jnp

        t = jnp.asarray(target)
        contrib = jnp.where(t > 0, t * (jnp.log(jnp.where(t > 0, t, 1.0)) - input), 0.0)
        loss = jnp.sum(contrib)
        return loss / input.size if self.size_average else loss
