"""Containers — Sequential, Concat, ConcatTable, ParallelTable, MapTable.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/Container.scala``,
``Sequential.scala``, ``Concat.scala`` — containers hold ``modules:
ArrayBuffer`` and compose child forward/backward calls.

TPU-native redesign: a container's ``init_params`` builds a nested dict
pytree keyed by ``"{index}:{child-name}"`` and its ``apply`` composes child
``apply`` calls — the whole tree traces into ONE XLA computation, so
containers are zero-cost at runtime (no per-layer dispatch like the
reference's JVM virtual calls into MKL).
"""

from __future__ import annotations

from typing import Any, Dict, List

from bigdl_tpu.nn.module import AbstractModule


class Container(AbstractModule):
    def __init__(self) -> None:
        super().__init__()
        self.modules: List[AbstractModule] = []

    def add(self, module: AbstractModule) -> "Container":
        self.modules.append(module)
        return self

    def sub_modules(self) -> List[AbstractModule]:
        return list(self.modules)

    def _child_key(self, i: int) -> str:
        return f"{i}:{self.modules[i].name}"

    def init_params(self, rng) -> Dict[str, Any]:
        import jax

        out = {}
        for i, m in enumerate(self.modules):
            out[self._child_key(i)] = m.init_params(jax.random.fold_in(rng, i))
        return out

    def init_state(self) -> Dict[str, Any]:
        return {self._child_key(i): m.init_state() for i, m in enumerate(self.modules)}

    def _child_rng(self, rng, i: int):
        if rng is None:
            return None
        import jax

        return jax.random.fold_in(rng, i)

    def __len__(self) -> int:
        return len(self.modules)

    def __getitem__(self, i: int) -> AbstractModule:
        return self.modules[i]


class Sequential(Container):
    """Feed-forward chain (reference ``nn/Sequential.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        new_state = {}
        x = input
        for i, m in enumerate(self.modules):
            k = self._child_key(i)
            x, s = m.apply(
                params.get(k, {}), x, state.get(k, {}),
                training=training, rng=self._child_rng(rng, i),
            )
            new_state[k] = s
        return x, new_state

    def __repr__(self) -> str:
        inner = " -> ".join(type(m).__name__ for m in self.modules)
        return f"Sequential({inner})"


class Concat(Container):
    """Apply every child to the same input, concatenate outputs along
    ``dimension`` (1-based, reference ``nn/Concat.scala``). Inception's
    building block."""

    def __init__(self, dimension: int) -> None:
        super().__init__()
        self.dimension = dimension

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        state = state or {}
        new_state = {}
        outs = []
        for i, m in enumerate(self.modules):
            k = self._child_key(i)
            o, s = m.apply(
                params.get(k, {}), input, state.get(k, {}),
                training=training, rng=self._child_rng(rng, i),
            )
            outs.append(o)
            new_state[k] = s
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state


class ConcatTable(Container):
    """Apply every child to the same input; output is the list of results
    (reference ``nn/ConcatTable.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        new_state = {}
        outs = []
        for i, m in enumerate(self.modules):
            k = self._child_key(i)
            o, s = m.apply(
                params.get(k, {}), input, state.get(k, {}),
                training=training, rng=self._child_rng(rng, i),
            )
            outs.append(o)
            new_state[k] = s
        return outs, new_state


class ParallelTable(Container):
    """i-th child consumes i-th element of the input list
    (reference ``nn/ParallelTable.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        new_state = {}
        outs = []
        for i, m in enumerate(self.modules):
            k = self._child_key(i)
            o, s = m.apply(
                params.get(k, {}), input[i], state.get(k, {}),
                training=training, rng=self._child_rng(rng, i),
            )
            outs.append(o)
            new_state[k] = s
        return outs, new_state


class MapTable(Container):
    """One shared child applied to every element of the input list
    (reference ``nn/MapTable.scala``). Parameters are shared across
    applications by construction (same pytree)."""

    def __init__(self, module: AbstractModule = None) -> None:
        super().__init__()
        if module is not None:
            self.add(module)

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        m = self.modules[0]
        k = self._child_key(0)
        outs = []
        s = state.get(k, {})
        for i, el in enumerate(input):
            o, s = m.apply(
                params.get(k, {}), el, s,
                training=training, rng=self._child_rng(rng, i),
            )
            outs.append(o)
        return outs, {k: s}


class Bottle(Container):
    """Reshape leading dims into one batch dim, apply child, restore
    (reference ``nn/Bottle.scala``; default nInputDim=2)."""

    def __init__(self, module: AbstractModule, n_input_dim: int = 2, n_output_dim: int = 2) -> None:
        super().__init__()
        self.add(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def apply(self, params, input, state=None, training=False, rng=None):
        state = state or {}
        k = self._child_key(0)
        shape = input.shape
        lead = shape[: len(shape) - self.n_input_dim + 1]
        rest = shape[len(shape) - self.n_input_dim + 1:]
        flat = input.reshape((-1,) + rest)
        out, s = self.modules[0].apply(
            params.get(k, {}), flat, state.get(k, {}), training=training, rng=rng
        )
        out = out.reshape(lead + out.shape[1:])
        return out, {k: s}


class Remat(Container):
    """Gradient checkpointing wrapper: the child's activations are NOT kept
    for the backward pass — they are recomputed (``jax.checkpoint``),
    trading FLOPs for HBM. No reference counterpart (the reference never
    ran out of accelerator memory); on TPU this is the standard lever for
    long-context / deep models (SURVEY.md hardware notes).

    Usage: ``Sequential().add(Remat(block1)).add(Remat(block2))``.
    """

    def __init__(self, module: AbstractModule) -> None:
        super().__init__()
        self.add(module)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        state = state or {}
        k = self._child_key(0)
        child = self.modules[0]

        def inner(p, x):
            return child.apply(p, x, state.get(k, {}),
                               training=training, rng=rng)

        out, s = jax.checkpoint(inner)(params.get(k, {}), input)
        return out, {k: s}
