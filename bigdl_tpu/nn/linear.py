"""Linear — fully-connected layer.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/Linear.scala`` — weight
shape ``(outputSize, inputSize)``, optional bias, gemm via
``DenseTensorBLAS``/MKL. Here the gemm is ``x @ W.T`` which XLA lowers
straight onto the MXU; fp32 params with, by default, highest matmul precision
to keep parity with the reference's fp32 MKL path (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.nn.module import TensorModule


class Linear(TensorModule):
    def __init__(
        self,
        input_size: int,
        output_size: int,
        with_bias: bool = True,
        w_regularizer=None,
        b_regularizer=None,
        init_weight: Optional[InitializationMethod] = None,
        init_bias: Optional[InitializationMethod] = None,
    ) -> None:
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None) -> "Linear":
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        p = {"weight": self.weight_init.init(k1, (self.output_size, self.input_size))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.output_size,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        out = jnp.matmul(input, params["weight"].T)
        if self.with_bias:
            out = out + params["bias"]
        return out, state

    def __repr__(self) -> str:
        return f"Linear({self.input_size} -> {self.output_size})"
