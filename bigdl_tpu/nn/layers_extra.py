"""Extended layer set (widening SURVEY.md §2.2's ~150-layer inventory).

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — similarity layers (``Cosine``, ``Euclidean``,
``DotProduct``, ``PairwiseDistance``, ``CosineDistance``), activations
(``SoftMin``, ``LogSigmoid``, ``Threshold``, ``RReLU``), shape/table ops
(``Replicate``, ``Index``, ``Masking``, ``SelectTable``, ``NarrowTable``,
``SpatialZeroPadding``, ``Scale``, ``GradientReversal``, ``L1Penalty``,
``GaussianSampler``), temporal/volumetric convolution and pooling, dilated
convolution and up-sampling.

All are pure ``apply`` functions over jax arrays; convolutions lower to
``lax.conv_general_dilated`` (MXU path), pooling to ``lax.reduce_window``,
and everything fuses under the train-step ``jit``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform
from bigdl_tpu.nn.module import AbstractModule, TensorModule
from bigdl_tpu.nn.shape_ops import _axis


# ---------------------------------------------------------------------------
# similarity / distance layers
# ---------------------------------------------------------------------------

def l2_normalize(x, axis: int = -1, eps: float = 1e-12):
    """x / max(||x||, eps) along ``axis`` — the shared clamped normalizer."""
    import jax.numpy as jnp

    return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), eps)


def cosine_similarity(x, y, axis: int = -1, eps: float = 1e-12):
    """Row-wise clamped cosine similarity (shared by the similarity layers
    and criterions; single definition so the epsilon policy can't drift)."""
    import jax.numpy as jnp

    num = jnp.sum(x * y, axis)
    den = jnp.maximum(
        jnp.linalg.norm(x, axis=axis) * jnp.linalg.norm(y, axis=axis), eps)
    return num / den


class Cosine(TensorModule):
    """(N, in) → (N, out): cosine similarity of x to each weight row
    (reference ``nn/Cosine.scala``)."""

    def __init__(self, input_size: int, output_size: int,
                 init_weight: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.weight_init = init_weight or RandomUniform()

    def init_params(self, rng):
        return {"weight": self.weight_init.init(
            rng, (self.output_size, self.input_size))}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.matmul(l2_normalize(input),
                          l2_normalize(params["weight"]).T), state


class Euclidean(TensorModule):
    """(N, in) → (N, out): L2 distance of x to each weight column
    (reference ``nn/Euclidean.scala``)."""

    def __init__(self, input_size: int, output_size: int,
                 init_weight: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.weight_init = init_weight or RandomUniform()

    def init_params(self, rng):
        return {"weight": self.weight_init.init(
            rng, (self.output_size, self.input_size))}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        diff = input[..., None, :] - params["weight"]       # (N, out, in)
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, -1), 1e-24)), state


class DotProduct(AbstractModule):
    """Table [x, y] → per-row dot product (reference ``nn/DotProduct.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, y = input
        return jnp.sum(x * y, -1), state


class PairwiseDistance(AbstractModule):
    """Table [x, y] → per-row Lp distance (reference ``nn/PairwiseDistance.scala``)."""

    def __init__(self, norm: int = 2) -> None:
        super().__init__()
        self.norm = norm

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, y = input
        d = jnp.abs(x - y) ** self.norm
        return jnp.sum(d, -1) ** (1.0 / self.norm), state


class CosineDistance(AbstractModule):
    """Table [x, y] → per-row cosine similarity (reference
    ``nn/CosineDistance.scala``; note: similarity, not 1−cos)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        x, y = input
        return cosine_similarity(x, y), state


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

class SoftMin(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.softmax(-input, axis=-1), state


class LogSigmoid(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        return jax.nn.log_sigmoid(input), state


class Threshold(TensorModule):
    """x if x > th else v (reference ``nn/Threshold.scala``)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0) -> None:
        super().__init__()
        self.th = th
        self.v = v

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.where(input > self.th, input, self.v), state


class RReLU(TensorModule):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training, the
    midpoint in evaluation (reference ``nn/RReLU.scala``)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3) -> None:
        super().__init__()
        self.lower = lower
        self.upper = upper

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        if training and rng is not None:
            a = jax.random.uniform(rng, input.shape,
                                   minval=self.lower, maxval=self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, a * input), state


# ---------------------------------------------------------------------------
# shape / table utilities
# ---------------------------------------------------------------------------

class Replicate(TensorModule):
    """Insert a new dim of size ``n_features`` at 1-based ``dim``
    (reference ``nn/Replicate.scala``)."""

    def __init__(self, n_features: int, dim: int = 1) -> None:
        super().__init__()
        self.n_features = n_features
        self.dim = dim

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = self.dim  # new axis goes AFTER the batch dim for 1-based dim
        return jnp.repeat(jnp.expand_dims(input, ax), self.n_features, ax), state


class Index(AbstractModule):
    """Table [tensor, 1-based indices] → ``take`` along ``dimension``
    (reference ``nn/Index.scala``)."""

    def __init__(self, dimension: int = 1) -> None:
        super().__init__()
        self.dimension = dimension

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, idx = input
        ax = _axis(self.dimension, x.ndim)
        return jnp.take(x, jnp.asarray(idx).astype(jnp.int32) - 1, axis=ax), state


class Masking(TensorModule):
    """Zero out timesteps equal to ``mask_value`` (reference
    ``nn/Masking.scala``): rows where EVERY feature == mask_value → 0."""

    def __init__(self, mask_value: float = 0.0) -> None:
        super().__init__()
        self.mask_value = mask_value

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        keep = jnp.any(input != self.mask_value, axis=-1, keepdims=True)
        return input * keep, state


class SelectTable(AbstractModule):
    """Pick element ``index`` (1-based; negative from the end) of a Table
    (reference ``nn/SelectTable.scala``)."""

    def __init__(self, index: int) -> None:
        super().__init__()
        self.index = index

    def apply(self, params, input, state=None, training=False, rng=None):
        i = self.index - 1 if self.index > 0 else len(input) + self.index
        return input[i], state


class NarrowTable(AbstractModule):
    """Slice a Table: ``length`` elements from 1-based ``offset``
    (reference ``nn/NarrowTable.scala``)."""

    def __init__(self, offset: int, length: int = 1) -> None:
        super().__init__()
        self.offset = offset
        self.length = length

    def apply(self, params, input, state=None, training=False, rng=None):
        out = list(input)[self.offset - 1: self.offset - 1 + self.length]
        return out, state


class SpatialZeroPadding(TensorModule):
    """Zero-pad H/W of an NCHW (or CHW) input (reference
    ``nn/SpatialZeroPadding.scala``); negative pads crop."""

    def __init__(self, pad_left: int, pad_right: int = None,
                 pad_top: int = None, pad_bottom: int = None) -> None:
        super().__init__()
        self.pl = pad_left
        self.pr = pad_right if pad_right is not None else pad_left
        self.pt = pad_top if pad_top is not None else pad_left
        self.pb = pad_bottom if pad_bottom is not None else pad_left

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x = input
        h_ax, w_ax = x.ndim - 2, x.ndim - 1
        # crops first (negative pads)
        def crop(a, ax, lo, hi):
            n = a.shape[ax]
            return jnp.take(a, jnp.arange(max(0, -lo), n - max(0, -hi)), ax)

        x = crop(x, h_ax, self.pt, self.pb)
        x = crop(x, w_ax, self.pl, self.pr)
        pads = [(0, 0)] * x.ndim
        pads[h_ax] = (max(0, self.pt), max(0, self.pb))
        pads[w_ax] = (max(0, self.pl), max(0, self.pr))
        return jnp.pad(x, pads), state


class Scale(TensorModule):
    """Learnable per-channel affine ``x*w + b`` (reference ``nn/Scale.scala``
    = CMul + CAdd), broadcast over an NCHW/feature layout."""

    def __init__(self, size: Sequence[int]) -> None:
        super().__init__()
        self.size = tuple(size)

    def init_params(self, rng):
        import jax.numpy as jnp

        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}

    def _broadcast(self, p, ndim):
        shape = (1,) + self.size + (1,) * (ndim - 1 - len(self.size))
        return p.reshape(shape)

    def apply(self, params, input, state=None, training=False, rng=None):
        w = self._broadcast(params["weight"], input.ndim)
        b = self._broadcast(params["bias"], input.ndim)
        return input * w + b, state


class GradientReversal(TensorModule):
    """Identity forward, ``-λ`` backward (reference
    ``nn/GradientReversal.scala``; domain-adversarial training)."""

    def __init__(self, the_lambda: float = 1.0) -> None:
        super().__init__()
        self.the_lambda = the_lambda

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        lam = self.the_lambda

        @jax.custom_vjp
        def rev(x):
            return x

        rev.defvjp(lambda x: (x, None), lambda _, ct: (-lam * ct,))
        return rev(input), state


class L1Penalty(TensorModule):
    """Identity forward that ADDS an L1 subgradient on the backward pass
    (reference ``nn/L1Penalty.scala``)."""

    def __init__(self, l1_weight: float, size_average: bool = False) -> None:
        super().__init__()
        self.l1_weight = l1_weight
        self.size_average = size_average

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        w = self.l1_weight
        avg = self.size_average

        @jax.custom_vjp
        def pen(x):
            return x

        def fwd(x):
            return x, x

        def bwd(x, ct):
            scale = w / x.size if avg else w
            return (ct + scale * jnp.sign(x),)

        pen.defvjp(fwd, bwd)
        return pen(input), state


class GaussianSampler(AbstractModule):
    """VAE reparameterization: input ``[mean, log_var]`` →
    ``mean + exp(log_var/2)·ε`` (reference ``nn/GaussianSampler.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        mean, log_var = input
        if rng is None:
            return mean, state
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps, state


class Negative(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return -input, state


# ---------------------------------------------------------------------------
# temporal / volumetric / dilated convolution + pooling
# ---------------------------------------------------------------------------

class TemporalConvolution(TensorModule):
    """1-D conv over (N, T, in) → (N, T', out) (reference
    ``nn/TemporalConvolution.scala``; time-major frames)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        return {
            "weight": self.weight_init.init(
                k1, (self.output_frame_size, self.input_frame_size,
                     self.kernel_w)),
            "bias": self.bias_init.init(k2, (self.output_frame_size,)),
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze = input.ndim == 2
        x = input[None] if squeeze else input          # (N, T, Cin)
        x = x.swapaxes(1, 2)                           # (N, Cin, T)
        out = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.stride_w,),
            padding="VALID", dimension_numbers=("NCH", "OIH", "NCH"),
        )
        out = out.swapaxes(1, 2) + params["bias"]
        return (out[0] if squeeze else out), state


class VolumetricConvolution(TensorModule):
    """3-D conv over (N, C, D, H, W) (reference
    ``nn/VolumetricConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.k = (k_t, k_h, k_w)
        self.d = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        p = {"weight": self.weight_init.init(
            k1, (self.n_output_plane, self.n_input_plane) + self.k)}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.n_output_plane,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze = input.ndim == 4
        x = input[None] if squeeze else input
        out = lax.conv_general_dilated(
            x, params["weight"], window_strides=self.d,
            padding=[(p, p) for p in self.pad],
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None, None]
        return (out[0] if squeeze else out), state


class _VolumetricPooling(TensorModule):
    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: int = None, d_w: int = None, d_h: int = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0) -> None:
        super().__init__()
        self.k = (k_t, k_h, k_w)
        self.d = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)


class VolumetricMaxPooling(_VolumetricPooling):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 4
        x = input[None] if squeeze else input
        out = lax.reduce_window(
            x, -jnp.inf, lax.max,
            window_dimensions=(1, 1) + self.k,
            window_strides=(1, 1) + self.d,
            padding=((0, 0), (0, 0)) + tuple((p, p) for p in self.pad),
        )
        return (out[0] if squeeze else out), state


class VolumetricAveragePooling(_VolumetricPooling):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import numpy as np

        squeeze = input.ndim == 4
        x = input[None] if squeeze else input
        sums = lax.reduce_window(
            x, 0.0, lax.add,
            window_dimensions=(1, 1) + self.k,
            window_strides=(1, 1) + self.d,
            padding=((0, 0), (0, 0)) + tuple((p, p) for p in self.pad),
        )
        out = sums / float(np.prod(self.k))
        return (out[0] if squeeze else out), state


class SpatialDilatedConvolution(TensorModule):
    """2-D conv with dilation (reference
    ``nn/SpatialDilatedConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        self.with_bias = with_bias
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        p = {"weight": self.weight_init.init(
            k1, (self.n_output_plane, self.n_input_plane, self.kh, self.kw))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.n_output_plane,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        out = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.dh, self.dw),
            padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            rhs_dilation=(self.dilation_h, self.dilation_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        return (out[0] if squeeze else out), state


class SpatialUpSamplingNearest(TensorModule):
    """Nearest-neighbour ×scale upsampling of NCHW (reference
    ``nn/SpatialUpSamplingNearest.scala``)."""

    def __init__(self, scale: int) -> None:
        super().__init__()
        self.scale = scale

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        s = self.scale
        x = input
        x = jnp.repeat(x, s, axis=x.ndim - 2)
        x = jnp.repeat(x, s, axis=x.ndim - 1)
        return x, state


class SpatialUpSamplingBilinear(TensorModule):
    """Bilinear ×scale upsampling (align_corners=True, the reference's
    semantics) of NCHW (reference ``nn/SpatialUpSamplingBilinear.scala``)."""

    def __init__(self, scale: int) -> None:
        super().__init__()
        self.scale = scale

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        n, c, h, w = x.shape
        oh, ow = h * self.scale, w * self.scale

        def grid(o, i):
            if o == 1 or i == 1:
                return jnp.zeros((o,))
            return jnp.arange(o) * (i - 1) / (o - 1)   # align_corners

        ys, xs = grid(oh, h), grid(ow, w)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
               + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
        return (out[0] if squeeze else out), state


class HardSigmoid(TensorModule):
    """clip(0.2x + 0.5, 0, 1) (reference keras-era ``HardSigmoid``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.clip(0.2 * input + 0.5, 0.0, 1.0), state


class TanhShrink(TensorModule):
    """x - tanh(x) (reference ``TanhShrink``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return input - jnp.tanh(input), state


class SoftShrink(TensorModule):
    """Soft shrinkage (reference ``SoftShrink``)."""

    def __init__(self, the_lambda: float = 0.5) -> None:
        super().__init__()
        self.the_lambda = the_lambda

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        lam = self.the_lambda
        return jnp.where(input > lam, input - lam,
                         jnp.where(input < -lam, input + lam, 0.0)), state


class HardShrink(TensorModule):
    """Hard shrinkage (reference ``HardShrink``)."""

    def __init__(self, the_lambda: float = 0.5) -> None:
        super().__init__()
        self.the_lambda = the_lambda

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        lam = self.the_lambda
        return jnp.where(jnp.abs(input) > lam, input, 0.0), state


class GaussianNoise(TensorModule):
    """Additive N(0, stddev²) noise in training (reference keras-era
    ``GaussianNoise``); identity at inference."""

    def __init__(self, stddev: float) -> None:
        super().__init__()
        self.stddev = stddev

    def apply(self, params, input, state=None, training=False, rng=None):
        if not training or rng is None:
            return input, state
        import jax

        return input + self.stddev * jax.random.normal(
            rng, input.shape, input.dtype), state


class GaussianDropout(TensorModule):
    """Multiplicative 1+N(0, rate/(1-rate)) noise in training (reference
    keras-era ``GaussianDropout``); identity at inference."""

    def __init__(self, rate: float) -> None:
        super().__init__()
        assert 0.0 <= rate < 1.0
        self.rate = rate

    def apply(self, params, input, state=None, training=False, rng=None):
        if not training or rng is None or self.rate == 0.0:
            return input, state
        import jax

        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, input.shape, input.dtype)
        return input * noise, state


class Bilinear(AbstractModule):
    """Two-input bilinear form: ``out_k = x1ᵀ W_k x2 + b_k`` over a Table
    ``[x1 (N,d1), x2 (N,d2)]`` (reference ``nn/Bilinear.scala``)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True,
                 init_weight: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.weight_init = init_weight or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        p = {"weight": self.weight_init.init(
            k1, (self.output_size, self.input_size1, self.input_size2))}
        if self.bias_res:
            p["bias"] = self.weight_init.init(k2, (self.output_size,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x1, x2 = input
        out = jnp.einsum("ni,oij,nj->no", x1, params["weight"], x2)
        if self.bias_res:
            out = out + params["bias"]
        return out, state
