"""Sparse-input layers: SparseLinear, SparseJoinTable.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/SparseLinear.scala``
(dense weight, sparse activations — the wide half of wide&deep models) and
``SparseJoinTable.scala`` (feature-wise concat of sparse inputs).

TPU-native: inputs are fixed-capacity COO :class:`SparseTensor`s; the matmul
is a gather + ``segment_sum`` (see ``tensor/sparse.py``) that XLA fuses
without densifying, and autodiff gives the dense weight gradient for free.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform
from bigdl_tpu.nn.module import AbstractModule
from bigdl_tpu.tensor.sparse import SparseTensor, sparse_dense_matmul, sparse_join


class SparseLinear(AbstractModule):
    """Linear over a sparse (B, in) activation; weight is dense (out, in)."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        p = {"weight": self.weight_init.init(k1, (self.output_size, self.input_size))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.output_size,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        assert isinstance(input, SparseTensor), (
            "SparseLinear wants a SparseTensor input"
        )
        out = sparse_dense_matmul(input, params["weight"].T)
        if self.with_bias:
            out = out + params["bias"]
        return out, state

    def __repr__(self) -> str:
        return f"SparseLinear({self.input_size} -> {self.output_size})"


class SparseJoinTable(AbstractModule):
    """Concatenate sparse inputs along ``dimension`` (1-based, reference
    semantics; 2 = feature dim)."""

    def __init__(self, dimension: int = 2) -> None:
        super().__init__()
        self.dimension = dimension

    def apply(self, params, input, state=None, training=False, rng=None):
        assert isinstance(input, (list, tuple)) and all(
            isinstance(t, SparseTensor) for t in input
        ), "SparseJoinTable wants a Table of SparseTensors"
        return sparse_join(list(input), self.dimension), state

    def __repr__(self) -> str:
        return f"SparseJoinTable(dim={self.dimension})"


class LookupTableSparse(AbstractModule):
    """Embedding bag over sparse id rows (reference
    ``nn/LookupTableSparse.scala``): input is a ``SparseTensor`` of 1-based
    ids shaped (batch, max_ids) — optionally a table with a second
    ``SparseTensor`` of per-id weights — reduced per row by ``combiner``
    ("sum" | "mean" | "sqrtn", the TF embedding_lookup_sparse semantics the
    reference mirrors).

    TPU-native: gather + ``segment_sum`` over the fixed COO capacity —
    static shapes, no densification; id 0 = padding slot contributes zero.
    """

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 init_weight: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        assert combiner in ("sum", "mean", "sqrtn"), combiner
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.weight_init = init_weight or RandomUniform()

    def init_params(self, rng):
        return {"weight": self.weight_init.init(
            rng, (self.n_index, self.n_output))}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        if isinstance(input, (list, tuple)):
            ids_sp, w_sp = input[0], input[1]
            weights = w_sp.values
        else:
            ids_sp, weights = input, None
        assert isinstance(ids_sp, SparseTensor), (
            "LookupTableSparse wants a SparseTensor of ids")
        rows = ids_sp.indices[0]
        ids = ids_sp.values.astype(jnp.int32)
        valid = (ids > 0).astype(params["weight"].dtype)
        w = valid if weights is None else weights * valid
        emb = params["weight"][jnp.maximum(ids - 1, 0)]     # (cap, dim)
        contrib = emb * w[:, None]
        batch = ids_sp.shape[0]
        out = jax.ops.segment_sum(contrib, rows, num_segments=batch)
        if self.combiner == "sum":
            return out, state
        if self.combiner == "mean":
            denom = jax.ops.segment_sum(w, rows, num_segments=batch)
        else:  # sqrtn
            denom = jnp.sqrt(
                jax.ops.segment_sum(w * w, rows, num_segments=batch))
        return out / jnp.maximum(denom, 1e-12)[:, None], state
