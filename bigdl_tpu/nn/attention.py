"""MultiHeadAttention layer (long-context extension; no reference
counterpart — SURVEY.md §5.7 documents the reference as attention-free).

A standard pre-projection MHA over ``(batch, time, hidden)`` activities that
slots into Sequential/Graph like any other layer. ``sequence_parallel``
selects the distributed attention algorithm when the model runs inside a
``shard_map`` with a sequence mesh axis:

* ``None``      — dense local attention (single chip / no SP)
* ``"ring"``    — blockwise ring attention over ``sp_axis`` (ICI ppermute)
* ``"ulysses"`` — all-to-all head-sharded attention over ``sp_axis``

The projections are plain MXU gemms; attention math lives in
``bigdl_tpu.parallel.ring_attention``.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import RandomUniform, Zeros, Xavier
from bigdl_tpu.nn.module import TensorModule


class MultiHeadAttention(TensorModule):
    # class-level defaults so instances deserialized from pre-use_flash
    # checkpoints (decoder bypasses __init__) still forward correctly
    use_flash = "auto"
    flash_block = None

    def __init__(self, hidden_size: int, n_heads: int, causal: bool = False,
                 sequence_parallel: Optional[str] = None,
                 sp_axis: str = "seq", use_flash: str = "auto",
                 flash_block: Optional[int] = None) -> None:
        super().__init__()
        if hidden_size % n_heads:
            raise ValueError(f"hidden {hidden_size} % heads {n_heads} != 0")
        if sequence_parallel not in (None, "ring", "striped_ring", "ulysses"):
            raise ValueError(f"unknown sequence_parallel {sequence_parallel!r}")
        if sequence_parallel == "striped_ring" and not causal:
            raise ValueError("striped_ring is a causal-only schedule — "
                             "use 'ring' for bidirectional attention")
        if sequence_parallel == "striped_ring" and use_flash == "never":
            raise ValueError("striped_ring has no non-flash path — it IS "
                             "a Pallas-kernel schedule; use 'ring' with "
                             "use_flash='never'")
        if use_flash not in ("auto", "always", "never"):
            raise ValueError(f"unknown use_flash {use_flash!r}")
        self.hidden_size = hidden_size
        self.n_heads = n_heads
        self.head_dim = hidden_size // n_heads
        self.causal = causal
        self.sequence_parallel = sequence_parallel
        self.sp_axis = sp_axis
        # local path kernel choice: the Pallas flash kernel
        # (bigdl_tpu.ops.flash_attention) on TPU, dense jnp otherwise
        self.use_flash = use_flash
        # VMEM tile length for the local flash path (None = _auto_block's
        # min(1024, T) — measured optimal in-model at T=2048, see
        # benchmarks/PERF_ANALYSIS_r5.md block sweep); exposed so the
        # sweep is runnable in-model rather than only standalone
        if flash_block is not None and (flash_block % 128 or flash_block <= 0):
            raise ValueError(
                f"flash_block must be a positive multiple of 128, "
                f"got {flash_block}")
        self.flash_block = flash_block

    def init_params(self, rng):
        import jax

        ks = jax.random.split(rng, 4)
        init = Xavier()
        H = self.hidden_size
        return {
            name: {"weight": init.init(k, (H, H)),
                   "bias": Zeros().init(k, (H,))}
            for name, k in zip(("wq", "wk", "wv", "wo"), ks)
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.parallel.ring_attention import (
            attention, ring_attention, ulysses_attention,
        )

        B, T, _ = input.shape

        def proj(p, x):
            return jnp.matmul(x, p["weight"].T) + p["bias"]

        def split(x):  # (B, T, H*D) -> (B, T, H, D)
            return x.reshape(B, T, self.n_heads, self.head_dim)

        q = split(proj(params["wq"], input))
        k = split(proj(params["wk"], input))
        v = split(proj(params["wv"], input))
        # one flash-eligibility policy for every dispatch branch
        flash_ok = self.use_flash == "always" or (
            self.use_flash == "auto" and jax.default_backend() == "tpu")
        if self.sequence_parallel == "ring":
            # ring rides the Pallas flash blocks when allowed (causal mode
            # uses the striped-causal merge: causal diagonal + LSE-nulled
            # future blocks)
            out = ring_attention(q, k, v, self.sp_axis, causal=self.causal,
                                 use_flash=flash_ok)
        elif self.sequence_parallel == "striped_ring":
            # balanced causal schedule: the SEQUENCE MUST BE IN STRIPE
            # LAYOUT (parallel.stripe_sequence on the global batch before
            # sharding; unstripe after the model) — every ring step then
            # does exactly half a block of useful work instead of a full
            # block with half discarded
            from bigdl_tpu.parallel.ring_attention import (
                striped_ring_attention,
            )

            out = striped_ring_attention(q, k, v, self.sp_axis)
        elif self.sequence_parallel == "ulysses":
            out = ulysses_attention(q, k, v, self.sp_axis, causal=self.causal,
                                    use_flash=flash_ok)
        elif flash_ok:
            from bigdl_tpu.ops import flash_attention

            out = flash_attention(q, k, v, causal=self.causal,
                                  block=self.flash_block)
        else:
            out = attention(q, k, v, causal=self.causal)
        out = out.reshape(B, T, self.hidden_size)
        return proj(params["wo"], out), state
