"""Sequence beam search (reference ``nn/SequenceBeamSearch.scala``).

The reference implements beam search as a layer driven by a
symbol-to-logits function (its transformer decoding path). TPU-native
redesign: the whole search is ONE ``lax.scan`` over the decode length with
static shapes throughout — alive/finished pools are fixed ``(batch, beam)``
tensors updated with ``top_k``/``take_along_axis`` (no data-dependent
control flow, so XLA compiles a single fused loop; length-penalty follows
the GNMT ``((5+len)/6)^alpha`` convention the reference uses).

Two surfaces:
- ``beam_search(...)`` — the pure function (jittable, vmappable).
- ``SequenceBeamSearch`` — module wrapper for API parity; its ``apply``
  treats the input as the per-example initial decoder carry and tiles it
  across beams.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from bigdl_tpu.nn.module import AbstractModule

_NEG = -1.0e9


def _length_penalty(length, alpha: float):
    return ((5.0 + length) / 6.0) ** alpha


def beam_search(
    step_fn: Callable[[Any, Any, Any], Any],
    params: Any,
    init_carry: Any,
    batch_size: int,
    beam_size: int,
    vocab_size: int,
    decode_length: int,
    sos_id: int = 1,
    eos_id: int = 2,
    alpha: float = 0.0,
    padding_value: Optional[int] = None,
):
    """Run beam search.

    ``step_fn(params, tokens (B·K,), carry) -> (logits (B·K, V), carry)``;
    every leaf of ``init_carry`` must have leading dim ``B·K`` (beam-major
    within each example). Returns ``(sequences (B, K, L), scores (B, K))``
    sorted best-first; rows with no finished beam fall back to alive beams.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, K, V, L = batch_size, beam_size, vocab_size, decode_length

    def gather_carry(tree, parents):
        """Select parent beams in every (B·K, ...) carry leaf."""

        def g(x):
            xs = x.reshape((B, K) + x.shape[1:])
            idx = parents.reshape((B, K) + (1,) * (xs.ndim - 2))
            out = jnp.take_along_axis(xs, idx, axis=1)
            return out.reshape((B * K,) + x.shape[1:])

        return jax.tree_util.tree_map(g, tree)

    seqs0 = jnp.full((B, K, L + 1), sos_id, jnp.int32)
    alive_logp0 = jnp.tile(
        jnp.asarray([[0.0] + [_NEG] * (K - 1)], jnp.float32), (B, 1))
    fin_seq0 = jnp.zeros((B, K, L + 1), jnp.int32)
    fin_scores0 = jnp.full((B, K), _NEG, jnp.float32)
    fin_flags0 = jnp.zeros((B, K), bool)

    def body(state, t):
        seqs, alive_logp, carry, fin_seq, fin_scores, fin_flags = state
        cur_tok = lax.dynamic_index_in_dim(seqs, t, axis=2, keepdims=False)
        logits, new_carry = step_fn(params, cur_tok.reshape(B * K), carry)
        logp = jax.nn.log_softmax(logits.reshape(B, K, V).astype(jnp.float32))
        flat = (alive_logp[..., None] + logp).reshape(B, K * V)
        top_lp, top_idx = lax.top_k(flat, 2 * K)          # (B, 2K)
        parents = top_idx // V
        toks = top_idx % V

        seq2 = jnp.take_along_axis(seqs, parents[:, :, None], axis=1)
        pos = jax.nn.one_hot(t + 1, L + 1, dtype=seq2.dtype)
        seq2 = seq2 * (1 - pos) + toks[:, :, None] * pos

        is_eos = toks == eos_id
        pen = _length_penalty((t + 1).astype(jnp.float32), alpha)
        fin_cand = jnp.where(is_eos, top_lp / pen, _NEG)

        all_seq = jnp.concatenate([fin_seq, seq2], axis=1)
        all_sc = jnp.concatenate([fin_scores, fin_cand], axis=1)
        all_fl = jnp.concatenate([fin_flags, is_eos], axis=1)
        sc, idx = lax.top_k(all_sc, K)
        fin_seq = jnp.take_along_axis(all_seq, idx[:, :, None], axis=1)
        fin_flags = jnp.take_along_axis(all_fl, idx, axis=1)
        fin_scores = sc

        alive_cand = jnp.where(is_eos, _NEG, top_lp)
        a_sc, a_idx = lax.top_k(alive_cand, K)
        seqs = jnp.take_along_axis(seq2, a_idx[:, :, None], axis=1)
        alive_parents = jnp.take_along_axis(parents, a_idx, axis=1)
        carry = gather_carry(new_carry, alive_parents)
        return (seqs, a_sc, carry, fin_seq, fin_scores, fin_flags), None

    state0 = (seqs0, alive_logp0, init_carry, fin_seq0, fin_scores0, fin_flags0)
    (seqs, alive_logp, _, fin_seq, fin_scores, fin_flags), _ = lax.scan(
        body, state0, jnp.arange(L))

    alive_scores = alive_logp / _length_penalty(jnp.float32(L), alpha)
    has_fin = jnp.any(fin_flags, axis=1)
    out_seq = jnp.where(has_fin[:, None, None], fin_seq, seqs)
    out_scores = jnp.where(has_fin[:, None], fin_scores, alive_scores)
    out_seq = out_seq[:, :, 1:]
    if padding_value is not None:
        # blank everything after the eos (exclusive: keep the eos itself)
        after_eos = jnp.cumsum((out_seq == eos_id).astype(jnp.int32),
                               axis=-1) - (out_seq == eos_id)
        out_seq = jnp.where(after_eos > 0, padding_value, out_seq)
    return out_seq, out_scores


class SequenceBeamSearch(AbstractModule):
    """Module facade over :func:`beam_search` (reference
    ``nn/SequenceBeamSearch.scala`` shape: construct with the vocabulary,
    beam width, length-penalty ``alpha`` and ids; feed the per-example
    decoder context as input).

    ``symbols_to_logits(params, tokens (N,), carry) -> (logits (N, V), carry)``
    closes over the caller's decoder modules; ``apply``'s input is the
    initial carry pytree with leading dim ``batch`` — it is tiled
    ``beam_size`` times here.
    """

    def __init__(self, symbols_to_logits: Callable, vocab_size: int,
                 beam_size: int, alpha: float = 0.0, decode_length: int = 32,
                 sos_id: int = 1, eos_id: int = 2,
                 padding_value: int = 0) -> None:
        super().__init__()
        self.symbols_to_logits = symbols_to_logits
        self.vocab_size = vocab_size
        self.beam_size = beam_size
        self.alpha = alpha
        self.decode_length = decode_length
        self.sos_id = sos_id
        self.eos_id = eos_id
        self.padding_value = padding_value

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(input)
        batch = leaves[0].shape[0]
        tiled = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, self.beam_size, axis=0), input)
        out = beam_search(
            self.symbols_to_logits, params, tiled, batch, self.beam_size,
            self.vocab_size, self.decode_length, self.sos_id, self.eos_id,
            self.alpha, self.padding_value)
        return list(out), state
