"""Normalization layers.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/BatchNormalization.scala``
(running mean/var buffers, ``momentum``, ``eps``, affine),
``SpatialBatchNormalization.scala`` (per-channel over N×H×W, NCHW),
``SpatialCrossMapLRN.scala`` (AlexNet/Inception local response norm).

TPU-native: running statistics live in the module's **state pytree**, updated
functionally (``apply`` returns the new state) — this is what lets the whole
train step stay jittable while preserving the reference's stateful-buffer
semantics. Torch conventions kept for oracle parity: normalize with biased
batch variance, store unbiased variance in the running buffer, running update
``r = (1-momentum)*r + momentum*batch``.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, Ones, Zeros
from bigdl_tpu.nn.module import TensorModule


class BatchNormalization(TensorModule):
    """1-D batch norm over (N, D) input."""

    _reduce_axes = (0,)
    _param_shape_fn = staticmethod(lambda n, nd: (n,))

    def __init__(
        self,
        n_output: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        init_weight: Optional[InitializationMethod] = None,
        init_bias: Optional[InitializationMethod] = None,
    ) -> None:
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.weight_init = init_weight or Ones()
        self.bias_init = init_bias or Zeros()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def init_params(self, rng):
        if not self.affine:
            return {}
        import jax

        k1, k2 = jax.random.split(rng)
        return {
            "weight": self.weight_init.init(k1, (self.n_output,)),
            "bias": self.bias_init.init(k2, (self.n_output,)),
        }

    def init_state(self):
        import jax.numpy as jnp

        return {
            "running_mean": jnp.zeros((self.n_output,)),
            "running_var": jnp.ones((self.n_output,)),
        }

    def _broadcast(self, v, ndim: int):
        if ndim == 2:
            return v[None, :]
        return v[None, :, None, None]

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        axes = tuple(i for i in range(input.ndim) if i != 1)
        if training:
            # accumulate in at least fp32, preserving fp64 when x64 is on
            acc_dtype = jnp.promote_types(input.dtype, jnp.float32)
            xf = input.astype(acc_dtype)
            mean = jnp.mean(xf, axis=axes)
            if jnp.finfo(input.dtype).bits >= jnp.finfo(acc_dtype).bits:
                # no accumulator headroom over the data: the fused form
                # E[x²]−E[x]² would cancel catastrophically for large means
                var = jnp.var(xf, axis=axes)
            else:
                # sub-fp32 inputs: the fused single-pass form lets XLA fold
                # both reductions into ONE read of the activations, and the
                # wider accumulator has headroom over bf16/f16 data
                var = jnp.maximum(
                    jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
            n = 1
            for i in axes:
                n *= input.shape[i]
            unbiased = var * (n / max(n - 1, 1))
            # running stats stay fp32 end to end
            new_state = {
                "running_mean": (1 - self.momentum) * state["running_mean"]
                + self.momentum * mean,
                "running_var": (1 - self.momentum) * state["running_var"]
                + self.momentum * unbiased,
            }
        else:
            mean = state["running_mean"]
            var = state["running_var"]
            new_state = state
        inv = 1.0 / jnp.sqrt(var + self.eps)
        # only the per-channel factors downcast; the elementwise math stays
        # in the input dtype (upcasting whole activations would double HBM
        # traffic and erase the mixed-precision win)
        mean = mean.astype(input.dtype)
        inv = inv.astype(input.dtype)
        out = (input - self._broadcast(mean, input.ndim)) * self._broadcast(
            inv, input.ndim
        )
        if self.affine:
            out = out * self._broadcast(params["weight"], input.ndim) + self._broadcast(
                params["bias"], input.ndim
            )
        return out, new_state


class SpatialBatchNormalization(BatchNormalization):
    """Per-channel BN over (N, C, H, W) — same math, channel axis 1."""


class SpatialCrossMapLRN(TensorModule):
    """Local response normalization across channels:
    ``out = x / (k + alpha/size * sum_window x^2)^beta``."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0) -> None:
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        sq = x * x
        half = (self.size - 1) // 2
        # sum x^2 over a window of `size` channels centered at each channel
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, self.size, 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)),
        )
        denom = (self.k + (self.alpha / self.size) * window_sum) ** self.beta
        out = x / denom
        if squeeze_batch:
            out = out[0]
        return out, state


class Normalize(TensorModule):
    """Lp-normalize along dim 1 (reference ``nn/Normalize.scala``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10) -> None:
        super().__init__()
        self.p = p
        self.eps = eps

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        norm = jnp.sum(jnp.abs(input) ** self.p, axis=1, keepdims=True) ** (
            1.0 / self.p
        )
        return input / (norm + self.eps), state
