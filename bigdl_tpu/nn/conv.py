"""Spatial convolutions.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/SpatialConvolution.scala``
— im2col into per-thread ``fInput`` buffers followed by an MKL gemm; weight
laid out ``(nGroup, out/g, in/g, kH, kW)``; argument order is
``(nInputPlane, nOutputPlane, kW, kH, dW, dH, padW, padH, nGroup,
propagateBack)`` (width before height, Torch style).

TPU-native redesign: im2col disappears entirely — ``lax.conv_general_dilated``
lowers to the MXU's native convolution path, which is the whole point of the
TPU engine (SURVEY.md §7: "im2col-free conv comes from XLA itself"). Weight
is stored OIHW (groups folded into O) and grouping uses XLA's
``feature_group_count``. Activations use NCHW dimension numbers for
reference semantic parity (weight/bias shapes, Reshape arithmetic); XLA's
layout assignment re-tiles internally for the hardware.

``padW = padH = -1`` selects SAME padding, as in the reference.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform
from bigdl_tpu.nn.module import TensorModule


class SpatialConvolution(TensorModule):
    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        n_group: int = 1,
        propagate_back: bool = True,
        with_bias: bool = True,
        w_regularizer=None,
        b_regularizer=None,
        init_weight: Optional[InitializationMethod] = None,
        init_bias: Optional[InitializationMethod] = None,
    ) -> None:
        super().__init__()
        assert n_input_plane % n_group == 0, "n_group must divide n_input_plane"
        assert n_output_plane % n_group == 0, "n_group must divide n_output_plane"
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None) -> "SpatialConvolution":
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        w_shape = (
            self.n_output_plane,
            self.n_input_plane // self.n_group,
            self.kernel_h,
            self.kernel_w,
        )
        p = {"weight": self.weight_init.init(k1, w_shape)}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.n_output_plane,))
        return p

    def _padding(self):
        if self.pad_w == -1 or self.pad_h == -1:
            return "SAME"
        return ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w))

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        out = lax.conv_general_dilated(
            x,
            params["weight"],
            window_strides=(self.stride_h, self.stride_w),
            padding=self._padding(),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        if squeeze_batch:
            out = out[0]
        return out, state

    def __repr__(self) -> str:
        return (
            f"SpatialConvolution({self.n_input_plane} -> {self.n_output_plane}, "
            f"{self.kernel_w}x{self.kernel_h}, {self.stride_w}x{self.stride_h}, "
            f"{self.pad_w},{self.pad_h})"
        )


class SpatialFullConvolution(TensorModule):
    """Transposed convolution (reference ``nn/SpatialFullConvolution.scala``);
    used by segmentation-style models and ``BilinearFiller`` upsampling."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        n_group: int = 1,
        no_bias: bool = False,
        init_weight: Optional[InitializationMethod] = None,
        init_bias: Optional[InitializationMethod] = None,
    ) -> None:
        super().__init__()
        assert n_input_plane % n_group == 0, "n_group must divide n_input_plane"
        assert n_output_plane % n_group == 0, "n_group must divide n_output_plane"
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.adj_w = adj_w
        self.adj_h = adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        # IOHW layout for transposed conv (input planes lead, reference-style)
        w_shape = (
            self.n_input_plane,
            self.n_output_plane // self.n_group,
            self.kernel_h,
            self.kernel_w,
        )
        p = {"weight": self.weight_init.init(k1, w_shape)}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.n_output_plane,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        # transposed conv == conv with lhs dilation (the gradient-of-conv
        # formulation); kernel goes (in, out/g, kh, kw) -> (out, in/g, kh, kw)
        # with spatial flip, grouped along the output dim
        g = self.n_group
        kh, kw = self.kernel_h, self.kernel_w
        w = params["weight"]
        in_pl = w.shape[0]
        w = w.reshape(g, in_pl // g, -1, kh, kw)
        w = jnp.swapaxes(w, 1, 2).reshape(-1, in_pl // g, kh, kw)
        w = w[:, :, ::-1, ::-1]
        out = lax.conv_general_dilated(
            x,
            w,
            window_strides=(1, 1),
            padding=(
                (kh - 1 - self.pad_h, kh - 1 - self.pad_h + self.adj_h),
                (kw - 1 - self.pad_w, kw - 1 - self.pad_w + self.adj_w),
            ),
            lhs_dilation=(self.stride_h, self.stride_w),
            feature_group_count=g,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        if squeeze_batch:
            out = out[0]
        return out, state
