"""Misc layers: Dropout, LookupTable, constants, reductions, MM/MV.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/Dropout.scala``
(scale-at-train-time), ``LookupTable.scala`` (embedding with optional
max-norm), ``MulConstant``/``AddConstant``/``Power``/``Square``/``Sqrt``,
``Mean``/``Max``/``Min``/``Sum``, ``MM``/``MV``, ``Mul``/``Add``/``CMul``/``CAdd``.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomNormal
from bigdl_tpu.nn.module import TensorModule
from bigdl_tpu.nn.shape_ops import _axis


class Dropout(TensorModule):
    """Inverted dropout: mask and scale by 1/(1-p) at train time only.

    TPU-native note: the bernoulli mask comes from the functional ``rng``
    threaded through ``apply`` — no stateful generator, so the train step
    stays jittable and reproducible.
    """

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True) -> None:
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p: float) -> "Dropout":
        self.p = p
        return self

    def apply(self, params, input, state=None, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return input, state
        import jax

        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, input.shape)
        out = input * mask
        if self.scale:
            out = out / keep
        return out, state


class LookupTable(TensorModule):
    """Embedding lookup; indices are 1-based like the reference.

    ``grad_via_matmul=True`` swaps the gather's scatter-add backward for a
    one-hot matmul ``dW = onehot(idx)^T @ dY`` with fp32 accumulation.
    Same math; f32 accumulate-then-round also beats the scatter's
    compute-dtype adds numerically. Honest measurement
    (benchmarks/llm_mfu_bench.py, 137M-param LM, 16k tokens x 32k vocab,
    v5e): the matmul path was ~5% SLOWER end-to-end than XLA's scatter
    lowering — the generated one-hot operand costs more than the scatter
    saves at this shape — so it stays default-off; the option remains for
    shapes where a scatter-heavy profile shows otherwise."""

    # class-level default: instances deserialized from older checkpoints
    # bypass __init__
    grad_via_matmul = False

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False,
                 init_weight: Optional[InitializationMethod] = None,
                 grad_via_matmul: bool = False) -> None:
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = int(padding_value)
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.grad_via_matmul = grad_via_matmul
        self.weight_init = init_weight or RandomNormal(0.0, 1.0)

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init = weight_init
        return self

    def init_params(self, rng):
        return {"weight": self.weight_init.init(rng, (self.n_index, self.n_output))}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        w = params["weight"]
        if self.max_norm != float("inf"):
            norms = jnp.sum(jnp.abs(w) ** self.norm_type, axis=1, keepdims=True) ** (
                1.0 / self.norm_type
            )
            w = w * jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
        idx = input.astype(jnp.int32) - 1  # 1-based reference indices
        take = (_take_with_matmul_grad(self.n_index)
                if self.grad_via_matmul else
                lambda w_, i_: jnp.take(w_, i_, axis=0))
        out = take(w, jnp.clip(idx, 0, self.n_index - 1))
        # ids < 1 (the text pipeline's padding id 0) embed to the zero
        # vector — static-shape-friendly padding with no dedicated pad row
        out = jnp.where((idx < 0)[..., None], 0.0, out)
        if self.padding_value != 0:
            pad_mask = (input.astype(jnp.int32) == self.padding_value)
            out = jnp.where(pad_mask[..., None], 0.0, out)
        return out, state


def _take_with_matmul_grad(n_rows: int):
    """``take(w, idx, axis=0)`` whose VJP computes ``dW`` as a one-hot
    matmul (MXU, fp32 accumulation) instead of a scatter-add."""
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def take2(w, idx):
        return jnp.take(w, idx, axis=0)

    def fwd(w, idx):
        return jnp.take(w, idx, axis=0), idx

    def bwd(idx, g):
        flat = idx.reshape(-1)
        gf = g.reshape(-1, g.shape[-1])
        onehot = jax.nn.one_hot(flat, n_rows, dtype=gf.dtype)
        dw = jnp.matmul(onehot.T, gf,
                        preferred_element_type=jnp.float32)
        return dw.astype(g.dtype), None

    take2.defvjp(fwd, bwd)
    return take2


class MulConstant(TensorModule):
    def __init__(self, scalar: float, inplace: bool = False) -> None:
        super().__init__()
        self.scalar = scalar

    def apply(self, params, input, state=None, training=False, rng=None):
        return input * self.scalar, state


class AddConstant(TensorModule):
    def __init__(self, constant_scalar: float, inplace: bool = False) -> None:
        super().__init__()
        self.constant_scalar = constant_scalar

    def apply(self, params, input, state=None, training=False, rng=None):
        return input + self.constant_scalar, state


class Power(TensorModule):
    """out = (shift + scale * x) ** power (reference ``nn/Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0) -> None:
        super().__init__()
        self.power = power
        self.scale = scale
        self.shift = shift

    def apply(self, params, input, state=None, training=False, rng=None):
        return (self.shift + self.scale * input) ** self.power, state


class Square(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return input * input, state


class Sqrt(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.sqrt(input), state


class Abs(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.abs(input), state


class Log(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.log(input), state


class Exp(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.exp(input), state


class Clamp(TensorModule):
    def __init__(self, min_v: float, max_v: float) -> None:
        super().__init__()
        self.min_v = min_v
        self.max_v = max_v

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.clip(input, self.min_v, self.max_v), state


class _Reduction(TensorModule):
    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True) -> None:
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def _ax(self, input):
        return _axis(self.dimension, input.ndim, self.n_input_dims)


class Mean(_Reduction):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.mean(input, axis=self._ax(input), keepdims=not self.squeeze), state


class Sum(_Reduction):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.sum(input, axis=self._ax(input), keepdims=not self.squeeze), state


class Max(_Reduction):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.max(input, axis=self._ax(input), keepdims=not self.squeeze), state


class Min(_Reduction):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.min(input, axis=self._ax(input), keepdims=not self.squeeze), state


class MM(TensorModule):
    """Batch/plain matmul of a two-tensor table (reference ``nn/MM.scala``)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False) -> None:
        super().__init__()
        self.trans_a = trans_a
        self.trans_b = trans_b

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), state


class MV(TensorModule):
    def __init__(self, trans: bool = False) -> None:
        super().__init__()
        self.trans = trans

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state


class Mul(TensorModule):
    """Learnable scalar gain (reference ``nn/Mul.scala``)."""

    def init_params(self, rng):
        import jax

        return {"weight": jax.random.uniform(rng, (), minval=-1.0, maxval=1.0)}

    def apply(self, params, input, state=None, training=False, rng=None):
        return input * params["weight"], state


class Add(TensorModule):
    """Learnable bias vector (reference ``nn/Add.scala``)."""

    def __init__(self, input_size: int) -> None:
        super().__init__()
        self.input_size = input_size

    def init_params(self, rng):
        import jax.numpy as jnp

        return {"bias": jnp.zeros((self.input_size,))}

    def apply(self, params, input, state=None, training=False, rng=None):
        return input + params["bias"], state


class CMul(TensorModule):
    """Learnable per-element gain with broadcast shape (reference ``nn/CMul.scala``)."""

    def __init__(self, size) -> None:
        super().__init__()
        self.size = tuple(size)

    def init_params(self, rng):
        import jax

        import numpy as np

        fan = max(int(np.prod(self.size)), 1)
        bound = 1.0 / np.sqrt(fan)
        return {"weight": jax.random.uniform(rng, self.size, minval=-bound, maxval=bound)}

    def apply(self, params, input, state=None, training=False, rng=None):
        return input * params["weight"], state


class CAdd(TensorModule):
    def __init__(self, size) -> None:
        super().__init__()
        self.size = tuple(size)

    def init_params(self, rng):
        import jax.numpy as jnp

        return {"bias": jnp.zeros(self.size)}

    def apply(self, params, input, state=None, training=False, rng=None):
        return input + params["bias"], state
