"""Spatial pooling layers.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/SpatialMaxPooling.scala``,
``SpatialAveragePooling.scala`` — Torch argument order ``(kW, kH, dW, dH,
padW, padH)``; ``.ceil()`` switches output-size rounding (Inception-v1 uses
ceil-mode max pooling).

TPU-native: ``lax.reduce_window`` — XLA lowers windowed reductions natively;
ceil mode becomes explicit extra right/bottom padding with the reduction
identity (−inf for max, 0 for average).
"""

from __future__ import annotations

import math

from bigdl_tpu.nn.module import TensorModule


class _SpatialPooling(TensorModule):
    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0) -> None:
        super().__init__()
        self.kw = kw
        self.kh = kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _out_size(self, in_size: int, k: int, d: int, p: int) -> int:
        if p == -1:  # reference convention: -1 = TF-style SAME
            return -(-in_size // d)
        if self.ceil_mode:
            out = int(math.ceil((in_size + 2 * p - k) / d)) + 1
        else:
            out = int(math.floor((in_size + 2 * p - k) / d)) + 1
        if p > 0 and (out - 1) * d >= in_size + p:
            out -= 1  # last window must start inside the (left-padded) input
        return out

    def _pads(self, h: int, w: int):
        """(low, high) padding per spatial dim incl. ceil-mode extra."""
        if self.pad_h == -1 or self.pad_w == -1:  # TF-style SAME
            oh = -(-h // self.dh)
            ow = -(-w // self.dw)
            th = max((oh - 1) * self.dh + self.kh - h, 0)
            tw = max((ow - 1) * self.dw + self.kw - w, 0)
            return (th // 2, th - th // 2), (tw // 2, tw - tw // 2)
        oh = self._out_size(h, self.kh, self.dh, self.pad_h)
        ow = self._out_size(w, self.kw, self.dw, self.pad_w)
        extra_h = max((oh - 1) * self.dh + self.kh - h - 2 * self.pad_h, 0)
        extra_w = max((ow - 1) * self.dw + self.kw - w - 2 * self.pad_w, 0)
        return (self.pad_h, self.pad_h + extra_h), (self.pad_w, self.pad_w + extra_w)


class SpatialMaxPooling(_SpatialPooling):
    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        ph, pw = self._pads(x.shape[2], x.shape[3])
        out = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            window_dimensions=(1, 1, self.kh, self.kw),
            window_strides=(1, 1, self.dh, self.dw),
            padding=((0, 0), (0, 0), ph, pw),
        )
        if squeeze_batch:
            out = out[0]
        return out, state


class SpatialAveragePooling(_SpatialPooling):
    def __init__(
        self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
        global_pooling: bool = False,
        ceil_mode: bool = False,
        count_include_pad: bool = True,
        divide: bool = True,
    ) -> None:
        super().__init__(kw, kh, dw, dh, pad_w, pad_h)
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        if self.global_pooling:
            kh, kw = x.shape[2], x.shape[3]
        else:
            kh, kw = self.kh, self.kw
        self_kh, self_kw = self.kh, self.kw
        self.kh, self.kw = kh, kw  # so _pads sees effective kernel
        ph, pw = self._pads(x.shape[2], x.shape[3])
        self.kh, self.kw = self_kh, self_kw
        sums = lax.reduce_window(
            x, 0.0, lax.add,
            window_dimensions=(1, 1, kh, kw),
            window_strides=(1, 1, self.dh, self.dw),
            padding=((0, 0), (0, 0), ph, pw),
        )
        if not self.divide:
            out = sums
        elif self.count_include_pad:
            out = sums / float(kh * kw)
        else:
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(
                ones, 0.0, lax.add,
                window_dimensions=(1, 1, kh, kw),
                window_strides=(1, 1, self.dh, self.dw),
                padding=((0, 0), (0, 0), ph, pw),
            )
            out = sums / counts
        if squeeze_batch:
            out = out[0]
        return out, state
