"""Graph — functional DAG API.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/Graph.scala`` /
``StaticGraph.scala`` + ``utils/Node.scala`` — ``module.inputs(prevNodes...)``
builds edges, ``Graph(input, output)`` topologically sorts and executes. The
ResNet/Inception zoo is built on this.

TPU-native: the DAG is walked once at trace time inside ``apply``; XLA sees a
flat computation, so graph execution order costs nothing at runtime. Shared
modules (same instance at several nodes) naturally share one params subtree —
keyed by module name — which reproduces the reference's weight-sharing
semantics without its clone/share machinery.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Union

from bigdl_tpu.nn.module import AbstractModule, Identity


class ModuleNode:
    """DAG node: a module plus its predecessor nodes (reference ``Node``)."""

    def __init__(self, module: AbstractModule, prev: Sequence["ModuleNode"] = ()) -> None:
        self.module = module
        self.prev: List[ModuleNode] = list(prev)

    def __repr__(self) -> str:
        return f"Node({self.module.name})"


def Input() -> ModuleNode:
    """Placeholder input node (reference ``Input()``)."""
    return ModuleNode(Identity().set_name(f"Input{id(object())%100000}"), ())


def _inputs(self: AbstractModule, *nodes: ModuleNode) -> ModuleNode:
    """``module.inputs(n1, n2, ...)`` — attach and return this module's node."""
    return ModuleNode(self, nodes)


AbstractModule.inputs = _inputs  # reference API: module.inputs(...)


def _as_list(x) -> List[Any]:
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class Graph(AbstractModule):
    def __init__(
        self,
        input: Union[ModuleNode, Sequence[ModuleNode]],
        output: Union[ModuleNode, Sequence[ModuleNode]],
    ) -> None:
        super().__init__()
        self.input_nodes = _as_list(input)
        self.output_nodes = _as_list(output)
        self._single_input = not isinstance(input, (list, tuple))
        self._single_output = not isinstance(output, (list, tuple))
        self.topo: List[ModuleNode] = self._topo_sort()
        self._rebuild_keys()

    def _rebuild_keys(self) -> None:
        """Derive params keys from topo order + module names. Keys are
        position-based (never ``id()``-based) so they are stable across
        serialization round-trips; called again from ``__setstate__``."""
        self._module_keys: Dict[int, str] = {}
        seen: Dict[int, AbstractModule] = {}
        for node in self.topo:
            mid = id(node.module)
            if mid not in seen:
                seen[mid] = node.module
                self._module_keys[mid] = f"{len(seen) - 1}:{node.module.name}"
        self._distinct_modules = list(seen.values())

    def __getstate__(self):
        d = super().__getstate__()
        # id()-keyed caches don't survive a round-trip; rebuilt on load
        d.pop("_module_keys", None)
        d.pop("_distinct_modules", None)
        return d

    def __setstate__(self, d):
        super().__setstate__(d)
        self._rebuild_keys()

    def _topo_sort(self) -> List[ModuleNode]:
        order: List[ModuleNode] = []
        visited: Dict[int, int] = {}  # 0=visiting, 1=done

        def visit(n: ModuleNode) -> None:
            vid = id(n)
            if visited.get(vid) == 1:
                return
            if visited.get(vid) == 0:
                raise ValueError("Graph contains a cycle")
            visited[vid] = 0
            for p in n.prev:
                visit(p)
            visited[vid] = 1
            order.append(n)

        for out in self.output_nodes:
            visit(out)
        for inp in self.input_nodes:
            if id(inp) not in visited:
                raise ValueError(f"input node {inp} is not connected to any output")
        return order

    def sub_modules(self) -> List[AbstractModule]:
        return list(self._distinct_modules)

    def init_params(self, rng):
        import jax

        out = {}
        for i, m in enumerate(self._distinct_modules):
            out[self._module_keys[id(m)]] = m.init_params(jax.random.fold_in(rng, i))
        return out

    def init_state(self):
        return {self._module_keys[id(m)]: m.init_state() for m in self._distinct_modules}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        state = state or {}
        new_state = dict(state)
        values: Dict[int, Any] = {}
        inputs = _as_list(input) if not self._single_input else [input]
        if len(inputs) != len(self.input_nodes):
            raise ValueError(
                f"graph expects {len(self.input_nodes)} inputs, got {len(inputs)}"
            )
        for node, val in zip(self.input_nodes, inputs):
            values[id(node)] = val
        for i, node in enumerate(self.topo):
            nid = id(node)
            if nid in values:  # an input node
                continue
            args = [values[id(p)] for p in node.prev]
            x = args[0] if len(args) == 1 else args
            key = self._module_keys[id(node.module)]
            child_rng = None if rng is None else jax.random.fold_in(rng, i)
            out, s = node.module.apply(
                params.get(key, {}), x, new_state.get(key, {}),
                training=training, rng=child_rng,
            )
            values[nid] = out
            new_state[key] = s
        outs = [values[id(n)] for n in self.output_nodes]
        return (outs[0] if self._single_output else outs), new_state


StaticGraph = Graph
