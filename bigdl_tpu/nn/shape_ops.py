"""Shape / table manipulation layers.

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — ``Reshape``, ``View``, ``Select``, ``Narrow``,
``Squeeze``, ``Unsqueeze``, ``Transpose``, ``Padding``, ``JoinTable``,
``SplitTable``, ``CAddTable``/``CMulTable``/``CSubTable``/``CDivTable``,
``FlattenTable``. Dims and indices are 1-based like the reference; negative
dims count from the end.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from bigdl_tpu.nn.module import TensorModule


def _axis(dim: int, ndim: int, n_input_dims: int = -1) -> int:
    """1-based reference dim → 0-based axis, honoring the batch-dim
    convention: when the runtime tensor has one more dim than declared
    (``n_input_dims``), dim 1 refers to the first non-batch axis."""
    if dim < 0:
        return ndim + dim
    ax = dim - 1
    if 0 < n_input_dims < ndim:
        ax += ndim - n_input_dims
    return ax


class Reshape(TensorModule):
    """Reshape non-batch dims to ``size`` (reference ``nn/Reshape.scala``;
    ``batchMode=None`` auto-detects a leading batch dim)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = None) -> None:
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode
        self._n_element = int(np.prod(self.size))

    def apply(self, params, input, state=None, training=False, rng=None):
        total = int(np.prod(input.shape))
        batch = self.batch_mode
        if batch is None:
            batch = total != self._n_element
        if batch:
            return input.reshape((input.shape[0],) + self.size), state
        return input.reshape(self.size), state


class View(TensorModule):
    def __init__(self, *sizes: int) -> None:
        super().__init__()
        self.sizes = tuple(int(s) for s in sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n: int) -> "View":
        self.num_input_dims = n
        return self

    def apply(self, params, input, state=None, training=False, rng=None):
        total = int(np.prod(input.shape))
        if total != int(np.prod(self.sizes)):
            return input.reshape((input.shape[0],) + self.sizes), state
        return input.reshape(self.sizes), state


class Select(TensorModule):
    def __init__(self, dim: int, index: int) -> None:
        super().__init__()
        self.dim = dim
        self.index = index

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dim, input.ndim)
        idx = self.index - 1 if self.index > 0 else input.shape[ax] + self.index
        return jnp.take(input, idx, axis=ax), state


class Narrow(TensorModule):
    def __init__(self, dim: int, offset: int, length: int = 1) -> None:
        super().__init__()
        self.dim = dim
        self.offset = offset
        self.length = length

    def apply(self, params, input, state=None, training=False, rng=None):
        ax = _axis(self.dim, input.ndim)
        start = self.offset - 1 if self.offset > 0 else input.shape[ax] + self.offset
        length = self.length
        if length < 0:
            length = input.shape[ax] - start + 1 + length
        sl = [slice(None)] * input.ndim
        sl[ax] = slice(start, start + length)
        return input[tuple(sl)], state


class Squeeze(TensorModule):
    def __init__(self, dim: int = None, num_input_dims: int = -1) -> None:
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        if self.dim is None:
            return jnp.squeeze(input), state
        ax = _axis(self.dim, input.ndim, self.num_input_dims)
        return jnp.squeeze(input, axis=ax), state


class Unsqueeze(TensorModule):
    def __init__(self, pos: int, num_input_dims: int = -1) -> None:
        super().__init__()
        self.pos = pos
        self.num_input_dims = num_input_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.pos, input.ndim + 1,
                   self.num_input_dims + 1 if self.num_input_dims > 0 else -1)
        return jnp.expand_dims(input, axis=ax), state


class Transpose(TensorModule):
    """Swap listed (1-based) dim pairs in order (reference ``nn/Transpose.scala``)."""

    def __init__(self, permutations: Sequence[Sequence[int]]) -> None:
        super().__init__()
        self.permutations = [tuple(p) for p in permutations]

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        out = input
        for d1, d2 in self.permutations:
            out = jnp.swapaxes(out, _axis(d1, out.ndim), _axis(d2, out.ndim))
        return out, state


class Contiguous(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return input, state


class Padding(TensorModule):
    """Pad ``pad`` entries (negative = before, positive = after) along ``dim``
    with ``value`` (reference ``nn/Padding.scala``)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1) -> None:
        super().__init__()
        self.dim = dim
        self.pad = pad
        self.n_input_dim = n_input_dim
        self.value = value

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dim, input.ndim, self.n_input_dim)
        widths = [(0, 0)] * input.ndim
        widths[ax] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, widths, constant_values=self.value), state


# ---------------------------------------------------------------------------
# table (multi-input) arithmetic
# ---------------------------------------------------------------------------


class CAddTable(TensorModule):
    """Sum a list of tensors (reference ``nn/CAddTable.scala``) — the residual
    join in ResNet graphs."""

    def __init__(self, inplace: bool = False) -> None:
        super().__init__()

    def apply(self, params, input, state=None, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out + x
        return out, state


class CMulTable(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        out = input[0]
        for x in input[1:]:
            out = out * x
        return out, state


class CSubTable(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return input[0] - input[1], state


class CDivTable(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return input[0] / input[1], state


class CMaxTable(TensorModule):
    """Elementwise max over a Table (reference ``nn/CMaxTable.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import functools

        import jax.numpy as jnp

        return functools.reduce(jnp.maximum, input), state


class CMinTable(TensorModule):
    """Elementwise min over a Table (reference ``nn/CMinTable.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import functools

        import jax.numpy as jnp

        return functools.reduce(jnp.minimum, input), state


class JoinTable(TensorModule):
    """Concatenate a list along ``dimension`` (reference ``nn/JoinTable.scala``).
    ``n_input_dims`` handles the implicit batch dim as in the reference."""

    def __init__(self, dimension: int, n_input_dims: int = -1) -> None:
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dimension, input[0].ndim, self.n_input_dims)
        return jnp.concatenate(list(input), axis=ax), state


class SplitTable(TensorModule):
    """Split along ``dimension`` into a list (reference ``nn/SplitTable.scala``)."""

    def __init__(self, dimension: int, n_input_dims: int = -1) -> None:
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dimension, input.ndim, self.n_input_dims)
        n = input.shape[ax]
        return [jnp.take(input, i, axis=ax) for i in range(n)], state


class FlattenTable(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        flat = []

        def rec(x):
            if isinstance(x, (list, tuple)):
                for v in x:
                    rec(v)
            else:
                flat.append(x)

        rec(input)
        return flat, state


class CAveTable(TensorModule):
    """Elementwise average over a Table (reference ``nn/CAveTable.scala``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        import functools
        import operator

        return functools.reduce(operator.add, input) / len(input), state
