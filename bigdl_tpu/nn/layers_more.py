"""Third layer-breadth batch (SURVEY.md §2.2 "~150 layers" inventory).

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — table/shape utilities (``Pack``, ``Tile``, ``Reverse``,
``InferReshape``, ``BifurcateSplitTable``, ``MixtureTable``,
``MaskedSelect``), keras-heritage activations (``SReLU``, ``Maxout``),
unshared/locally-connected and separable convolutions
(``LocallyConnected1D/2D``, ``SpatialSeparableConvolution``,
``SpatialShareConvolution``), volumetric transposed convolution,
temporal pooling, up-sampling/cropping, channel-wise dropout
(``SpatialDropout1D/2D/3D``), and the LeCun-era local normalization family
(``SpatialWithinChannelLRN``, ``SpatialSubtractiveNormalization``,
``SpatialDivisiveNormalization``, ``SpatialContrastiveNormalization``).

TPU-native notes: everything stays statically shaped for XLA except
``MaskedSelect``/``DenseToSparse`` which are host-side by nature (their
output shape is data-dependent); locally-connected layers lower to
``conv_general_dilated_patches`` + one einsum (a single MXU contraction
instead of the reference's per-position gemm loop); the normalization
family lowers to ``lax.conv_general_dilated`` with SAME-style coverage
correction so it fuses under jit.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.nn.init_methods import (
    InitializationMethod, RandomUniform, Xavier, Zeros,
)
from bigdl_tpu.nn.module import AbstractModule, TensorModule
from bigdl_tpu.nn.shape_ops import _axis


# ---------------------------------------------------------------------------
# table / shape utilities
# ---------------------------------------------------------------------------

class Pack(AbstractModule):
    """Stack a table of same-shaped tensors along a new 1-based ``dim``
    (reference ``nn/Pack.scala``)."""

    def __init__(self, dim: int = 1) -> None:
        super().__init__()
        self.dim = dim

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        seq = input if isinstance(input, (list, tuple)) else [input]
        return jnp.stack(list(seq), axis=self.dim - 1), state


class Tile(AbstractModule):
    """Concatenate ``copies`` copies of the input along 1-based ``dim``
    (reference ``nn/Tile.scala``)."""

    def __init__(self, dim: int = 1, copies: int = 2) -> None:
        super().__init__()
        self.dim = dim
        self.copies = copies

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dim, input.ndim)
        return jnp.concatenate([input] * self.copies, axis=ax), state


class Reverse(AbstractModule):
    """Flip the input along 1-based ``dim`` (reference ``nn/Reverse.scala``;
    used by ``BiRecurrent`` for the backward leg)."""

    def __init__(self, dim: int = 1) -> None:
        super().__init__()
        self.dim = dim

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.flip(input, axis=_axis(self.dim, input.ndim)), state


class InferReshape(AbstractModule):
    """Reshape with inference: ``-1`` infers one dim, ``0`` copies the input's
    dim at the same position (reference ``nn/InferReshape.scala``).
    ``batch_mode=True`` preserves the leading batch dim."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False) -> None:
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def _target(self, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        body = in_shape[1:] if self.batch_mode else in_shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(body[i])
            else:
                out.append(s)  # -1 handled by reshape itself
        if self.batch_mode:
            return (in_shape[0],) + tuple(out)
        return tuple(out)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.reshape(input, self._target(input.shape)), state


class BifurcateSplitTable(AbstractModule):
    """Split a tensor into two halves along 1-based ``dim`` → table of two
    (reference ``nn/BifurcateSplitTable.scala``)."""

    def __init__(self, dim: int = 1) -> None:
        super().__init__()
        self.dim = dim

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        ax = _axis(self.dim, input.ndim)
        n = input.shape[ax]
        assert n % 2 == 0, "BifurcateSplitTable needs an even dim"
        a, b = jnp.split(input, 2, axis=ax)
        return [a, b], state


class MixtureTable(AbstractModule):
    """Mixture-of-experts combine: table ``[gater (B,E), experts]`` where
    experts is a table of E tensors ``(B, ...)`` or one tensor ``(B, E, ...)``;
    output = gate-weighted sum over experts (reference ``nn/MixtureTable.scala``).

    TPU-native: the table form stacks once and contracts with an einsum —
    XLA turns it into a single fused reduce, no per-expert loop.
    """

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        gater, experts = input[0], input[1]
        if isinstance(experts, (list, tuple)):
            experts = jnp.stack(list(experts), axis=1)  # (B, E, ...)
        g = gater.reshape(gater.shape + (1,) * (experts.ndim - 2))
        return jnp.sum(g * experts, axis=1), state


class MaskedSelect(AbstractModule):
    """Table ``[x, mask]`` → 1-D tensor of the elements where mask is nonzero
    (reference ``nn/MaskedSelect.scala``).

    Output shape is data-dependent, so this is a HOST-side op (outside jit) —
    the same boundary the reference drew by running it on the JVM heap.
    """

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x, mask = input
        xh = np.asarray(x)
        mh = np.asarray(mask).astype(bool)
        return jnp.asarray(xh[mh]), state


class DenseToSparse(AbstractModule):
    """Convert a dense tensor to the fixed-capacity COO ``SparseTensor``
    (reference ``nn/DenseToSparse.scala``). Host-side: nnz is data-dependent;
    pass ``capacity`` to pre-pad for a downstream jitted sparse layer."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        super().__init__()
        self.capacity = capacity

    def apply(self, params, input, state=None, training=False, rng=None):
        from bigdl_tpu.tensor.sparse import SparseTensor

        return SparseTensor.from_dense(np.asarray(input), self.capacity), state


# ---------------------------------------------------------------------------
# parameterized activations
# ---------------------------------------------------------------------------

class SReLU(TensorModule):
    """S-shaped ReLU (reference ``nn/SReLU.scala``, keras heritage):

    ``f(x) = t_r + a_r (x - t_r)`` for ``x >= t_r``; ``x`` in the middle band;
    ``t_l + a_l (x - t_l)`` for ``x <= t_l`` — all four thresholds/slopes
    learned per-feature, with ``shared_axes`` collapsing broadcast axes."""

    def __init__(self, shape: Sequence[int],
                 shared_axes: Optional[Sequence[int]] = None) -> None:
        super().__init__()
        self.shape = tuple(int(s) for s in shape)
        self.shared_axes = tuple(shared_axes or ())

    def _param_shape(self) -> Tuple[int, ...]:
        return tuple(
            1 if (i + 1) in self.shared_axes else s
            for i, s in enumerate(self.shape)
        )

    def init_params(self, rng):
        import jax.numpy as jnp

        shp = self._param_shape()
        k = Xavier().init(rng, shp).astype(jnp.float32)
        return {
            "t_left": jnp.zeros(shp, jnp.float32),
            "a_left": jnp.full(shp, 0.0, jnp.float32),
            "t_right": k,
            "a_right": jnp.ones(shp, jnp.float32),
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        out = jnp.where(input >= tr, tr + ar * (input - tr), input)
        out = jnp.where(input <= tl, tl + al * (input - tl), out)
        return out, state


class Maxout(TensorModule):
    """Maxout feature layer (reference ``nn/Maxout.scala``): a Linear to
    ``output_size * maxout_number`` followed by max over each pool — one MXU
    gemm + a reshape/reduce that XLA fuses."""

    def __init__(self, input_size: int, output_size: int, maxout_number: int,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.with_bias = with_bias
        self.weight_init = init_weight or Xavier()
        self.bias_init = init_bias or Zeros()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        n = self.output_size * self.maxout_number
        p = {"weight": self.weight_init.init(k1, (n, self.input_size))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (n,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        h = jnp.matmul(input, params["weight"].T)
        if self.with_bias:
            h = h + params["bias"]
        h = h.reshape(h.shape[:-1] + (self.output_size, self.maxout_number))
        return jnp.max(h, axis=-1), state


# ---------------------------------------------------------------------------
# temporal pooling / up-sampling / cropping
# ---------------------------------------------------------------------------

class TemporalMaxPooling(TensorModule):
    """Max pooling over the time axis of ``(B, T, F)`` / ``(T, F)`` input
    (reference ``nn/TemporalMaxPooling.scala``). ``pad_mode="SAME"`` is the
    keras border_mode="same" extension (TF-style same padding)."""

    # class-level default: snapshots saved before pad_mode existed restore
    # via __new__ + attribute dict and must keep loading (VALID behavior)
    pad_mode = "VALID"

    def __init__(self, k_w: int, d_w: Optional[int] = None,
                 pad_mode: str = "VALID") -> None:
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w or k_w
        self.pad_mode = pad_mode

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 2
        x = input[None] if squeeze else input
        out = lax.reduce_window(
            x, -jnp.inf, lax.max,
            window_dimensions=(1, self.k_w, 1),
            window_strides=(1, self.d_w, 1),
            padding=self.pad_mode,
        )
        return (out[0] if squeeze else out), state


class TemporalAveragePooling(TensorModule):
    """Average pooling over the time axis of ``(B, T, F)`` / ``(T, F)``
    input — the 1-D analog of ``SpatialAveragePooling`` (keras
    AveragePooling1D's core). SAME mode EXCLUDES padding from the divisor
    at clipped edge windows, matching Keras-1.2/TF semantics."""

    pad_mode = "VALID"  # back-compat default for pre-pad_mode snapshots

    def __init__(self, k_w: int, d_w: Optional[int] = None,
                 pad_mode: str = "VALID") -> None:
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w or k_w
        self.pad_mode = pad_mode

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 2
        x = input[None] if squeeze else input
        sums = lax.reduce_window(
            x, 0.0, lax.add,
            window_dimensions=(1, self.k_w, 1),
            window_strides=(1, self.d_w, 1),
            padding=self.pad_mode,
        )
        if self.pad_mode == "SAME":
            # counts depend only on the time axis — O(T), broadcast over
            # batch/features in the division
            counts = lax.reduce_window(
                jnp.ones((1, x.shape[1], 1), x.dtype), 0.0, lax.add,
                window_dimensions=(1, self.k_w, 1),
                window_strides=(1, self.d_w, 1),
                padding="SAME",
            )
            out = sums / counts
        else:
            out = sums / float(self.k_w)
        return (out[0] if squeeze else out), state


class VolumetricZeroPadding(TensorModule):
    """Zero-pad the three spatial dims of (N, C, D, H, W) input
    (reference ``nn/VolumetricZeroPadding? — keras ZeroPadding3D core``;
    symmetric ``(pad_t, pad_h, pad_w)``)."""

    def __init__(self, pad_t: int = 1, pad_h: int = 1, pad_w: int = 1) -> None:
        super().__init__()
        self.pads = (pad_t, pad_h, pad_w)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        squeeze = input.ndim == 4
        x = input[None] if squeeze else input
        widths = [(0, 0), (0, 0)] + [(p, p) for p in self.pads]
        out = jnp.pad(x, widths)
        return (out[0] if squeeze else out), state


class UpSampling1D(TensorModule):
    """Repeat each timestep ``length`` times: ``(B, T, F) → (B, T*length, F)``
    (reference ``nn/UpSampling1D.scala``)."""

    def __init__(self, length: int = 2) -> None:
        super().__init__()
        self.length = length

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.repeat(input, self.length, axis=-2), state


class UpSampling3D(TensorModule):
    """Nearest-neighbor volumetric up-sampling of NCDHW input by integer
    factors (reference ``nn/UpSampling3D.scala``)."""

    def __init__(self, size: Sequence[int] = (2, 2, 2)) -> None:
        super().__init__()
        self.size = tuple(int(s) for s in size)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        out = input
        for i, f in enumerate(self.size):
            out = jnp.repeat(out, f, axis=out.ndim - 3 + i)
        return out, state


class Cropping2D(TensorModule):
    """Crop rows/cols off NCHW input: ``height_crop=(top, bottom)``,
    ``width_crop=(left, right)`` (reference ``nn/Cropping2D.scala``)."""

    def __init__(self, height_crop: Sequence[int] = (0, 0),
                 width_crop: Sequence[int] = (0, 0),
                 data_format: str = "NCHW") -> None:
        super().__init__()
        self.hc = tuple(height_crop)
        self.wc = tuple(width_crop)
        assert data_format in ("NCHW", "NHWC")
        self.data_format = data_format

    def apply(self, params, input, state=None, training=False, rng=None):
        (t, b), (l, r) = self.hc, self.wc
        h_ax = -3 if self.data_format == "NHWC" else -2
        w_ax = -2 if self.data_format == "NHWC" else -1
        idx = [slice(None)] * input.ndim
        idx[h_ax] = slice(t, input.shape[h_ax] - b)
        idx[w_ax] = slice(l, input.shape[w_ax] - r)
        return input[tuple(idx)], state


class Cropping3D(TensorModule):
    """Crop the three spatial dims of NCDHW input (reference
    ``nn/Cropping3D.scala``)."""

    def __init__(self, dim1_crop: Sequence[int] = (0, 0),
                 dim2_crop: Sequence[int] = (0, 0),
                 dim3_crop: Sequence[int] = (0, 0)) -> None:
        super().__init__()
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))

    def apply(self, params, input, state=None, training=False, rng=None):
        idx = [slice(None)] * input.ndim
        for i, (lo, hi) in enumerate(self.crops):
            ax = input.ndim - 3 + i
            idx[ax] = slice(lo, input.shape[ax] - hi)
        return input[tuple(idx)], state


# ---------------------------------------------------------------------------
# convolution variants
# ---------------------------------------------------------------------------

class VolumetricFullConvolution(TensorModule):
    """3-D transposed convolution over NCDHW input (reference
    ``nn/VolumetricFullConvolution.scala``) — conv with lhs dilation, the
    gradient-of-conv formulation (mirrors ``SpatialFullConvolution``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.k = (k_t, k_h, k_w)
        self.d = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.n_group = n_group
        self.with_bias = not no_bias
        self.weight_init = init_weight or RandomUniform()
        self.bias_init = init_bias or RandomUniform()

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        kt, kh, kw = self.k
        w_shape = (self.n_input_plane, self.n_output_plane // self.n_group,
                   kt, kh, kw)
        p = {"weight": self.weight_init.init(k1, w_shape)}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k2, (self.n_output_plane,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 4
        x = input[None] if squeeze else input
        g = self.n_group
        kt, kh, kw = self.k
        w = params["weight"]
        in_pl = w.shape[0]
        w = w.reshape(g, in_pl // g, -1, kt, kh, kw)
        w = jnp.swapaxes(w, 1, 2).reshape(-1, in_pl // g, kt, kh, kw)
        w = w[:, :, ::-1, ::-1, ::-1]
        pads = tuple(
            (k - 1 - p, k - 1 - p + a)
            for k, p, a in zip(self.k, self.pad, self.adj)
        )
        out = lax.conv_general_dilated(
            x, w, window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=self.d, feature_group_count=g,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None, None]
        return (out[0] if squeeze else out), state


class LocallyConnected2D(TensorModule):
    """Unshared convolution: a distinct kernel per output position
    (reference ``nn/LocallyConnected2D.scala``).

    TPU-native: patches via ``conv_general_dilated_patches`` then ONE einsum
    ``(N,K,P) × (P,O,K) → (N,O,P)`` — a single batched MXU contraction in
    place of the reference's per-position gemm loop."""

    def __init__(self, n_input_plane: int, input_width: int, input_height: int,
                 n_output_plane: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.n_input_plane = n_input_plane
        self.input_width = input_width
        self.input_height = input_height
        self.n_output_plane = n_output_plane
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.with_bias = with_bias
        self.weight_init = init_weight or Xavier()
        self.bias_init = init_bias or Zeros()
        self.out_h = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        self.out_w = (input_width + 2 * pad_w - kernel_w) // stride_w + 1

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        npos = self.out_h * self.out_w
        kdim = self.n_input_plane * self.kernel_h * self.kernel_w
        p = {"weight": self.weight_init.init(
            k1, (npos, self.n_output_plane, kdim))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(
                k2, (self.n_output_plane, self.out_h, self.out_w))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        patches = lax.conv_general_dilated_patches(
            x, (self.kernel_h, self.kernel_w),
            (self.stride_h, self.stride_w),
            ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # (N, C*kh*kw, oh, ow)
        n = patches.shape[0]
        k = patches.shape[1]
        patches = patches.reshape(n, k, -1)                   # (N, K, P)
        out = jnp.einsum("nkp,pok->nop", patches, params["weight"])
        out = out.reshape(n, self.n_output_plane, self.out_h, self.out_w)
        if self.with_bias:
            out = out + params["bias"][None]
        return (out[0] if squeeze else out), state


class LocallyConnected1D(TensorModule):
    """Unshared temporal convolution over ``(B, T, F)`` input (reference
    ``nn/LocallyConnected1D.scala``); weight per output frame."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.n_input_frame = n_input_frame
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.weight_init = init_weight or Xavier()
        self.bias_init = init_bias or Zeros()
        self.out_t = (n_input_frame - kernel_w) // stride_w + 1

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        kdim = self.input_frame_size * self.kernel_w
        p = {"weight": self.weight_init.init(
            k1, (self.out_t, self.output_frame_size, kdim))}
        if self.with_bias:
            p["bias"] = self.bias_init.init(
                k2, (self.out_t, self.output_frame_size))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 2
        x = input[None] if squeeze else input            # (B, T, F)
        # one patch-extraction op (feature-major (F, k) flattened channels),
        # then a single batched MXU contraction — no per-position slicing
        patches = lax.conv_general_dilated_patches(
            jnp.swapaxes(x, 1, 2), (self.kernel_w,), (self.stride_w,),
            "VALID", dimension_numbers=("NCH", "OIH", "NCH"),
        )                                                 # (B, F*k, oT)
        patches = jnp.swapaxes(patches, 1, 2)             # (B, P, K)
        out = jnp.einsum("bpk,pok->bpo", patches, params["weight"])
        if self.with_bias:
            out = out + params["bias"][None]
        return (out[0] if squeeze else out), state


class SpatialShareConvolution(TensorModule):
    """Reference ``nn/SpatialShareConvolution.scala`` — numerically identical
    to ``SpatialConvolution``; the reference variant only shares its im2col
    buffers across clones. With XLA there are no such buffers, so this is the
    same MXU convolution (kept as its own class for API parity)."""

    def __init__(self, *args, **kwargs) -> None:
        super().__init__()
        from bigdl_tpu.nn.conv import SpatialConvolution

        self._conv = SpatialConvolution(*args, **kwargs)
        # mirror attrs for repr/introspection parity
        self.n_input_plane = self._conv.n_input_plane
        self.n_output_plane = self._conv.n_output_plane

    def set_init_method(self, weight_init=None, bias_init=None):
        self._conv.set_init_method(weight_init, bias_init)
        return self

    def init_params(self, rng):
        return self._conv.init_params(rng)

    def apply(self, params, input, state=None, training=False, rng=None):
        return self._conv.apply(params, input, state, training, rng)


class SpatialSeparableConvolution(TensorModule):
    """Depthwise-separable convolution (reference
    ``nn/SpatialSeparableConvolution.scala``): depthwise conv with
    ``depth_multiplier`` channels per input plane, then a 1×1 pointwise conv.
    Lowers to two ``conv_general_dilated`` calls — the depthwise leg uses
    ``feature_group_count = n_input_channel`` (XLA's native depthwise path)."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, k_w: int, k_h: int,
                 s_w: int = 1, s_h: int = 1, p_w: int = 0, p_h: int = 0,
                 with_bias: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.n_input_channel = n_input_channel
        self.n_output_channel = n_output_channel
        self.depth_multiplier = depth_multiplier
        self.k = (k_h, k_w)
        self.s = (s_h, s_w)
        self.p = (p_h, p_w)
        self.with_bias = with_bias
        self.weight_init = init_weight or Xavier()
        self.bias_init = init_bias or Zeros()

    def init_params(self, rng):
        import jax

        k1, k2, k3 = jax.random.split(rng, 3)
        kh, kw = self.k
        depth_w = self.weight_init.init(
            k1, (self.n_input_channel * self.depth_multiplier, 1, kh, kw))
        point_w = self.weight_init.init(
            k2, (self.n_output_channel,
                 self.n_input_channel * self.depth_multiplier, 1, 1))
        p = {"depth_weight": depth_w, "point_weight": point_w}
        if self.with_bias:
            p["bias"] = self.bias_init.init(k3, (self.n_output_channel,))
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        ph, pw = self.p
        out = lax.conv_general_dilated(
            x, params["depth_weight"], window_strides=self.s,
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_input_channel,
        )
        out = lax.conv_general_dilated(
            out, params["point_weight"], window_strides=(1, 1),
            padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        return (out[0] if squeeze else out), state


# ---------------------------------------------------------------------------
# channel-wise dropout
# ---------------------------------------------------------------------------

class _SpatialDropoutNd(TensorModule):
    """Shared core: drop whole feature maps (noise broadcast over the spatial
    axes) — the reference's SpatialDropout family."""

    n_spatial = 2

    def __init__(self, init_p: float = 0.5) -> None:
        super().__init__()
        self.p = init_p

    def _noise_shape(self, shape):
        raise NotImplementedError

    def apply(self, params, input, state=None, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return input, state
        import jax

        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, self._noise_shape(input.shape))
        return input * mask / keep, state


class SpatialDropout1D(_SpatialDropoutNd):
    """Drop whole channels of ``(B, T, C)`` input (reference
    ``nn/SpatialDropout1D.scala``; keras convention — channels last)."""

    def _noise_shape(self, shape):
        return shape[:-2] + (1, shape[-1])


class SpatialDropout2D(_SpatialDropoutNd):
    """Drop whole feature maps of NCHW input (reference
    ``nn/SpatialDropout2D.scala``)."""

    def __init__(self, init_p: float = 0.5, data_format: str = "NCHW") -> None:
        super().__init__(init_p)
        assert data_format in ("NCHW", "NHWC")
        self.data_format = data_format

    def _noise_shape(self, shape):
        if self.data_format == "NCHW":
            return shape[:-2] + (1, 1)
        return shape[:-3] + (1, 1, shape[-1])


class SpatialDropout3D(_SpatialDropoutNd):
    """Drop whole feature volumes of NCDHW input (reference
    ``nn/SpatialDropout3D.scala``)."""

    def __init__(self, init_p: float = 0.5, data_format: str = "NCDHW") -> None:
        super().__init__(init_p)
        assert data_format in ("NCDHW", "NDHWC")
        self.data_format = data_format

    def _noise_shape(self, shape):
        if self.data_format == "NCDHW":
            return shape[:-3] + (1, 1, 1)
        return shape[:-4] + (1, 1, 1, shape[-1])


# ---------------------------------------------------------------------------
# local normalization family
# ---------------------------------------------------------------------------

def _local_mean_conv(x, kernel2d, n_channels):
    """Weighted local mean over ALL channels with border-coverage correction.

    Returns ``(mean_map (N,1,H,W), coef (1,1,H,W))`` where coef is the
    fraction of kernel mass inside the image at each position — dividing by
    it reproduces the reference's edge handling.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    kh, kw = kernel2d.shape
    # kernel normalized so full-coverage response is the mean across c,h,w
    k = kernel2d / (jnp.sum(kernel2d) * n_channels)
    w = jnp.broadcast_to(k, (1, n_channels, kh, kw)).astype(x.dtype)
    pad = ((kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2))
    mean = lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ones = jnp.ones((1, n_channels) + x.shape[-2:], x.dtype)
    coef = lax.conv_general_dilated(
        ones, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return mean, coef


class SpatialWithinChannelLRN(TensorModule):
    """Within-channel local response normalization (reference
    ``nn/SpatialWithinChannelLRN.scala``, caffe ``WITHIN_CHANNEL``):
    ``out = x / (1 + alpha/size² · Σ_window x²)^beta`` per channel."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75) -> None:
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        s = self.size
        pad = ((s // 2, (s - 1) // 2), (s // 2, (s - 1) // 2))
        sq_sum = lax.reduce_window(
            x * x, 0.0, lax.add, (1, 1, s, s), (1, 1, 1, 1),
            ((0, 0), (0, 0)) + pad,
        )
        out = x / (1.0 + (self.alpha / (s * s)) * sq_sum) ** self.beta
        return (out[0] if squeeze else out), state


class SpatialSubtractiveNormalization(TensorModule):
    """Subtract the kernel-weighted local mean (over all channels) from each
    pixel, with border-coverage correction (reference
    ``nn/SpatialSubtractiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None) -> None:
        super().__init__()
        self.n_input_plane = n_input_plane
        k = np.asarray(kernel if kernel is not None else np.ones((9, 9)),
                       np.float32)
        if k.ndim == 1:  # separable 1-D kernel → outer product
            k = np.outer(k, k)
        self.kernel = k

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        mean, coef = _local_mean_conv(
            x, jnp.asarray(self.kernel), self.n_input_plane)
        out = x - mean / coef
        return (out[0] if squeeze else out), state


class SpatialDivisiveNormalization(TensorModule):
    """Divide by the kernel-weighted local standard deviation, thresholded
    from below by its per-image mean (reference
    ``nn/SpatialDivisiveNormalization.scala``; Jarrett et al.'s
    ``v = x / max(mean(σ), σ_local)``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4) -> None:
        super().__init__()
        self.n_input_plane = n_input_plane
        k = np.asarray(kernel if kernel is not None else np.ones((9, 9)),
                       np.float32)
        if k.ndim == 1:
            k = np.outer(k, k)
        self.kernel = k
        self.threshold = threshold
        self.thresval = thresval

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        var, coef = _local_mean_conv(
            x * x, jnp.asarray(self.kernel), self.n_input_plane)
        local_std = jnp.sqrt(jnp.maximum(var / coef, 0.0))
        # sub-threshold stds are REPLACED by thresval (the reference's
        # Threshold(threshold, thresval) guard), then clamped from below by
        # the per-image mean std (Jarrett et al.'s max(mean σ, σ_local))
        local_std = jnp.where(local_std > self.threshold, local_std,
                              self.thresval)
        mean_std = jnp.mean(local_std, axis=(1, 2, 3), keepdims=True)
        out = x / jnp.maximum(local_std, mean_std)
        return (out[0] if squeeze else out), state


class SpatialContrastiveNormalization(TensorModule):
    """Subtractive then divisive normalization with one kernel (reference
    ``nn/SpatialContrastiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4) -> None:
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(
            n_input_plane, kernel, threshold, thresval)

    def apply(self, params, input, state=None, training=False, rng=None):
        out, state = self.sub.apply(params, input, state, training, rng)
        return self.div.apply(params, out, state, training, rng)


# ---------------------------------------------------------------------------
# penalty layers
# ---------------------------------------------------------------------------

class NegativeEntropyPenalty(TensorModule):
    """Identity forward; backward adds the gradient of
    ``beta · Σ p log p`` (negative entropy) — encourages high-entropy
    probability outputs (reference ``nn/NegativeEntropyPenalty.scala``)."""

    def __init__(self, beta: float = 0.01) -> None:
        super().__init__()
        self.beta = beta

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        beta = self.beta

        @jax.custom_vjp
        def pen(x):
            return x

        def fwd(x):
            return x, x

        def bwd(x, ct):
            return (ct + beta * (jnp.log(jnp.maximum(x, 1e-12)) + 1.0),)

        pen.defvjp(fwd, bwd)
        return pen(input), state


# ---------------------------------------------------------------------------
# connection-table convolution
# ---------------------------------------------------------------------------

class SpatialConvolutionMap(TensorModule):
    """Convolution over an explicit input→output plane connection table
    (reference ``nn/SpatialConvolutionMap.scala``, Torq heritage): one
    ``(kH, kW)`` kernel per table row, output plane o = Σ kernels whose row
    maps into o.

    TPU-native: the per-connection kernels scatter once into a dense
    ``(O, I, kH, kW)`` weight with zeros at non-connections (scatter indices
    are static), and the whole layer is ONE MXU convolution — no per-plane
    accumulation loop.
    """

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        table = np.asarray(conn_table, np.int32)
        assert table.ndim == 2 and table.shape[1] == 2, "conn_table is (K, 2)"
        self.conn_table = table  # 1-based (in_plane, out_plane) rows
        self.n_input_plane = int(table[:, 0].max())
        self.n_output_plane = int(table[:, 1].max())
        self.kernel_w = kernel_w
        self.kernel_h = kernel_h
        self.stride_w = stride_w
        self.stride_h = stride_h
        self.pad_w = pad_w
        self.pad_h = pad_h
        self.weight_init = init_weight or Xavier()
        self.bias_init = init_bias or Zeros()

    # reference table builders
    @staticmethod
    def full(n_in: int, n_out: int) -> np.ndarray:
        return np.array([(i + 1, o + 1) for o in range(n_out)
                         for i in range(n_in)], np.int32)

    @staticmethod
    def one_to_one(n: int) -> np.ndarray:
        return np.array([(i + 1, i + 1) for i in range(n)], np.int32)

    @staticmethod
    def random(n_in: int, n_out: int, fan_in: int, seed: int = 0) -> np.ndarray:
        rng = np.random.RandomState(seed)
        rows = []
        for o in range(n_out):
            for i in rng.choice(n_in, size=fan_in, replace=False):
                rows.append((i + 1, o + 1))
        return np.array(rows, np.int32)

    def init_params(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        k = self.conn_table.shape[0]
        return {
            "weight": self.weight_init.init(
                k1, (k, self.kernel_h, self.kernel_w)),
            "bias": self.bias_init.init(k2, (self.n_output_plane,)),
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        o_idx = self.conn_table[:, 1] - 1
        i_idx = self.conn_table[:, 0] - 1
        dense = jnp.zeros(
            (self.n_output_plane, self.n_input_plane,
             self.kernel_h, self.kernel_w), params["weight"].dtype,
        ).at[o_idx, i_idx].add(params["weight"])
        out = lax.conv_general_dilated(
            x, dense, (self.stride_h, self.stride_w),
            ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        out = out + params["bias"][None, :, None, None]
        return (out[0] if squeeze else out), state
