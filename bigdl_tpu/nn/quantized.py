"""Quantized inference path: int8 Linear / SpatialConvolution + Quantizer.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/quantized/`` —
``QuantizedModule``, int8 ``Linear``/``SpatialConvolution`` and ``Quantizer``
(``module.quantize()``) converting a trained float model for int8 inference.

TPU-native redesign: symmetric per-output-channel weight quantization to
int8 at conversion time + dynamic per-row activation quantization at run
time; the inner product runs as a TRUE int8×int8→int32 ``dot_general`` /
``conv_general_dilated`` (``preferred_element_type=int32``), then one fused
rescale back to float. Inference-only, like the reference.

Measured reality check (round 3, ``benchmarks/int8_bench.py`` on v5e):
XLA does NOT reach the MXU's nominal 2× int8 rate — int8 matmul times at
~0.85× the bf16 rate (131 TOP/s vs 154 TFLOP/s at 4096³), and with the
dynamic-quantization passes the end-to-end int8 ResNet-50 inference runs
at ~0.55× bf16. The path's value on TPU is the 4× weight footprint
(serving memory), with a measured ≤0.01 top-1 cost — not throughput.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.module import AbstractModule, TensorModule


def quantize_symmetric(w, axis):
    """Symmetric int8 quantization. ``axis``: dims reduced for the scale
    (everything except the output-channel dim). Returns (int8, f32 scale)."""
    import jax.numpy as jnp

    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


class QuantizedLinear(TensorModule):
    """int8 Linear built from a trained float ``Linear``.

    ``scheme="dynamic"`` quantizes activations per row at runtime and runs
    an int8×int8 dot (int32 accumulate) — measured 0.54× bf16 on v5e (XLA
    has no native-rate int8 lowering; the value is the 4× weight
    footprint). ``scheme="weight_only"`` keeps activations bf16 and
    dequantizes the int8 weights INTO the matmul (weights stay int8 in
    HBM — 4× less weight traffic — while the MXU runs at its full bf16
    rate and the dynamic-quant elementwise passes disappear); accuracy is
    at least the dynamic scheme's since activations are never rounded."""

    scheme = "dynamic"   # class default: pre-scheme pickles keep behavior

    def __init__(self, weight_q, w_scale, bias=None,
                 scheme: str = "dynamic") -> None:
        super().__init__()
        if scheme not in ("dynamic", "weight_only"):
            raise ValueError(f"unknown quantization scheme {scheme!r}")
        self._weight_q = weight_q       # (out, in) int8
        self._w_scale = w_scale         # (out, 1) f32
        self._bias = bias
        self.scheme = scheme

    @staticmethod
    def from_linear(lin, scheme: str = "dynamic") -> "QuantizedLinear":
        lin._materialize_params()
        wq, scale = quantize_symmetric(lin.params["weight"], axis=1)
        q = QuantizedLinear(wq, scale, lin.params.get("bias"), scheme)
        q.set_name(lin.name)
        q._ensure_params()
        return q

    def init_params(self, rng):
        p = {"weight_q": self._weight_q, "w_scale": self._w_scale}
        if self._bias is not None:
            p["bias"] = self._bias
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        x = input
        if getattr(self, "scheme", "dynamic") == "weight_only":
            # int8 weights convert to bf16 inside the dot's fusion (HBM
            # reads stay int8); per-channel scale applied on the output
            acc = lax.dot_general(
                x.astype(jnp.bfloat16),
                params["weight_q"].astype(jnp.bfloat16),
                (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            out = acc * params["w_scale"][:, 0]
        else:
            # dynamic symmetric per-row activation quantization
            x_amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
            x_scale = jnp.maximum(x_amax, 1e-8) / 127.0
            xq = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(
                xq, params["weight_q"],
                (((xq.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            out = acc.astype(jnp.float32) * x_scale * params["w_scale"][:, 0]
        if "bias" in params:
            out = out + params["bias"]
        return out, state

    def __repr__(self) -> str:
        o, i = self._weight_q.shape
        return f"QuantizedLinear({i} -> {o}, {self.scheme})"


class QuantizedSpatialConvolution(TensorModule):
    """int8 SpatialConvolution built from a trained float conv."""

    scheme = "dynamic"

    def __init__(self, conv, weight_q, w_scale, bias=None,
                 scheme: str = "dynamic") -> None:
        super().__init__()
        if scheme not in ("dynamic", "weight_only"):
            raise ValueError(f"unknown quantization scheme {scheme!r}")
        self.stride = (conv.stride_h, conv.stride_w)
        self.padding = conv._padding()
        self.n_group = conv.n_group
        self._weight_q = weight_q       # (O, I/g, kH, kW) int8
        self._w_scale = w_scale         # (O, 1, 1, 1) f32
        self._bias = bias
        self.scheme = scheme

    @staticmethod
    def from_conv(conv, scheme: str = "dynamic") -> "QuantizedSpatialConvolution":
        conv._materialize_params()
        wq, scale = quantize_symmetric(conv.params["weight"], axis=(1, 2, 3))
        q = QuantizedSpatialConvolution(conv, wq, scale,
                                        conv.params.get("bias"), scheme)
        q.set_name(conv.name)
        q._ensure_params()
        return q

    def init_params(self, rng):
        p = {"weight_q": self._weight_q, "w_scale": self._w_scale}
        if self._bias is not None:
            p["bias"] = self._bias
        return p

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.lax as lax
        import jax.numpy as jnp

        squeeze_batch = input.ndim == 3
        x = input[None] if squeeze_batch else input
        if getattr(self, "scheme", "dynamic") == "weight_only":
            acc = lax.conv_general_dilated(
                x.astype(jnp.bfloat16),
                params["weight_q"].astype(jnp.bfloat16),
                window_strides=self.stride,
                padding=self.padding,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=self.n_group,
                preferred_element_type=jnp.float32,
            )
            out = acc * params["w_scale"][None, :, 0, 0, 0][..., None, None]
        else:
            # per-image dynamic activation scale (one scalar per sample
            # keeps the conv a pure int8 op)
            x_amax = jnp.max(jnp.abs(x), axis=(1, 2, 3), keepdims=True)
            x_scale = jnp.maximum(x_amax, 1e-8) / 127.0
            xq = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
            acc = lax.conv_general_dilated(
                xq, params["weight_q"],
                window_strides=self.stride,
                padding=self.padding,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=self.n_group,
                preferred_element_type=jnp.int32,
            )
            out = acc.astype(jnp.float32) * x_scale * \
                params["w_scale"][None, :, 0, 0, 0][..., None, None]
        if "bias" in params:
            out = out + params["bias"][None, :, None, None]
        if squeeze_batch:
            out = out[0]
        return out, state

    def __repr__(self) -> str:
        o = self._weight_q.shape[0]
        return f"QuantizedSpatialConvolution(-> {o})"


class Quantizer:
    """``Quantizer.quantize(model)`` — walk the module tree, swapping each
    float Linear/SpatialConvolution for its int8 twin (reference
    ``module.quantize()``). The converted module keeps the original names so
    container/graph param keys stay stable."""

    @staticmethod
    def quantize(module: AbstractModule,
                 scheme: str = "dynamic") -> AbstractModule:
        """``scheme="dynamic"`` = int8×int8 with runtime activation
        quantization; ``scheme="weight_only"`` = int8 weights dequantized
        into bf16 matmuls (serving mode — see QuantizedLinear). Both keep
        the 4× weight-footprint win; throughput measured in
        benchmarks/int8_bench.py."""
        if scheme not in ("dynamic", "weight_only"):
            # fail fast even when no quantizable layer exists to catch it
            raise ValueError(f"unknown quantization scheme {scheme!r}")
        from bigdl_tpu.nn.conv import SpatialConvolution
        from bigdl_tpu.nn.linear import Linear

        module._materialize_params()
        Quantizer._push_params(module)

        def convert(m):
            if isinstance(m, Linear):
                return QuantizedLinear.from_linear(m, scheme)
            if isinstance(m, SpatialConvolution):
                return QuantizedSpatialConvolution.from_conv(m, scheme)
            return None

        new = convert(module)
        if new is not None:
            return new.evaluate()
        Quantizer._rewrite(module, convert)
        # reassemble the composite params bottom-up from the rewritten tree
        Quantizer._collect_params(module)
        module.grad_params = None
        module._ensure_params()
        return module.evaluate()

    @staticmethod
    def _collect_params(module: AbstractModule):
        from bigdl_tpu.nn.containers import Container
        from bigdl_tpu.nn.graph import Graph

        if isinstance(module, Container):
            for m in module.modules:
                Quantizer._collect_params(m)
            module.params = {
                module._child_key(i): (m.params or {})
                for i, m in enumerate(module.modules)
            }
            module.state = {
                module._child_key(i): (m.state or {})
                for i, m in enumerate(module.modules)
            }
        elif isinstance(module, Graph):
            for m in module._distinct_modules:
                Quantizer._collect_params(m)
            module.params = {
                module._module_keys[id(m)]: (m.params or {})
                for m in module._distinct_modules
            }
            module.state = {
                module._module_keys[id(m)]: (m.state or {})
                for m in module._distinct_modules
            }
        else:
            subs = module.sub_modules()
            if subs and isinstance(module.params, dict):
                for i, m in enumerate(subs):
                    key = f"{i}:{m.name}"
                    if key in module.params:
                        Quantizer._collect_params(m)
                        module.params[key] = m.params or {}
                        module.state[key] = m.state or {}
            else:
                module._materialize_params()

    @staticmethod
    def _push_params(module: AbstractModule) -> None:
        """Distribute a materialized composite's params down into each
        child's facade storage so from_linear/from_conv see trained weights."""
        from bigdl_tpu.nn.containers import Container
        from bigdl_tpu.nn.graph import Graph

        if isinstance(module, Container):
            for i, m in enumerate(module.modules):
                key = module._child_key(i)
                m.params = (module.params or {}).get(key, {})
                m.state = (module.state or {}).get(key, {})
                Quantizer._push_params(m)
        elif isinstance(module, Graph):
            for m in module._distinct_modules:
                key = module._module_keys[id(m)]
                m.params = (module.params or {}).get(key, {})
                m.state = (module.state or {}).get(key, {})
                Quantizer._push_params(m)
        else:
            # generic wrapper (TimeDistributed, Recurrent, keras layers, …):
            # children keyed by the uniform "{i}:{name}" convention; only
            # descend where the key actually matches, never guess
            for i, m in enumerate(module.sub_modules()):
                key = f"{i}:{m.name}"
                if isinstance(module.params, dict) and key in module.params:
                    m.params = module.params[key]
                    m.state = (module.state or {}).get(key, {})
                    Quantizer._push_params(m)

    @staticmethod
    def _rewrite(module: AbstractModule, convert) -> None:
        from bigdl_tpu.nn.containers import Container
        from bigdl_tpu.nn.graph import Graph

        if isinstance(module, Container):
            for i, m in enumerate(module.modules):
                new = convert(m)
                if new is not None:
                    module.modules[i] = new
                else:
                    Quantizer._rewrite(m, convert)
        elif isinstance(module, Graph):
            for node in module.topo:
                new = convert(node.module)
                if new is not None:
                    old = node.module
                    key = module._module_keys.pop(id(old))
                    module._module_keys[id(new)] = key
                    module._distinct_modules[
                        module._distinct_modules.index(old)] = new
                    # a module may back several nodes; patch them all
                    for n2 in module.topo:
                        if n2.module is old:
                            n2.module = new
                else:
                    Quantizer._rewrite(node.module, convert)
        else:
            # generic wrapper: replace AbstractModule-valued attributes
            for attr, val in list(vars(module).items()):
                if isinstance(val, AbstractModule):
                    new = convert(val)
                    if new is not None:
                        setattr(module, attr, new)
                    else:
                        Quantizer._rewrite(val, convert)
                elif isinstance(val, list):
                    for i, v in enumerate(val):
                        if isinstance(v, AbstractModule):
                            new = convert(v)
                            if new is not None:
                                val[i] = new
                            else:
                                Quantizer._rewrite(v, convert)
