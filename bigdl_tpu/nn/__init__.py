"""bigdl_tpu.nn — the NN module library (reference layer L2, SURVEY.md §2.2)."""

from bigdl_tpu.nn.module import AbstractModule, TensorModule, Identity, Echo
from bigdl_tpu.nn.containers import (
    Container, Sequential, Concat, ConcatTable, ParallelTable, MapTable, Bottle,
    Remat,
)
from bigdl_tpu.nn.graph import Graph, StaticGraph, Input, ModuleNode
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.conv import SpatialConvolution, SpatialFullConvolution
from bigdl_tpu.nn.pooling import SpatialMaxPooling, SpatialAveragePooling
from bigdl_tpu.nn.normalization import (
    BatchNormalization, SpatialBatchNormalization, SpatialCrossMapLRN, Normalize,
)
from bigdl_tpu.nn.activations import (
    ReLU, ReLU6, Tanh, Sigmoid, SoftMax, LogSoftMax, PReLU, ELU, LeakyReLU,
    HardTanh, SoftPlus, SoftSign, GELU,
)
from bigdl_tpu.nn.shape_ops import (
    Reshape, View, Select, Narrow, Squeeze, Unsqueeze, Transpose, Contiguous,
    Padding, CAddTable, CMulTable, CSubTable, CDivTable, CMaxTable, CMinTable,
    CAveTable,
    JoinTable, SplitTable,
    FlattenTable,
)
from bigdl_tpu.nn.misc import (
    Dropout, LookupTable, MulConstant, AddConstant, Power, Square, Sqrt, Abs,
    Log, Exp, Clamp, Mean, Sum, Max, Min, MM, MV, Mul, Add, CMul, CAdd,
)
from bigdl_tpu.nn.attention import MultiHeadAttention
from bigdl_tpu.nn.recurrent import (
    Cell, ConvLSTMPeephole, RnnCell, LSTM, LSTMPeephole, GRU, Recurrent,
    BiRecurrent,
    RecurrentDecoder, TimeDistributed, MultiRNNCell,
)
from bigdl_tpu.nn.criterion import (
    AbstractCriterion, ClassNLLCriterion, CrossEntropyCriterion, MSECriterion,
    AbsCriterion, BCECriterion, SmoothL1Criterion, MultiLabelSoftMarginCriterion,
    ParallelCriterion, TimeDistributedCriterion, MarginCriterion,
    DistKLDivCriterion,
)
from bigdl_tpu.nn.criterion_extra import (
    ClassSimplexCriterion, CosineProximityCriterion, SoftMarginCriterion,
    CosineDistanceCriterion, CosineEmbeddingCriterion,
    DiceCoefficientCriterion, GaussianCriterion, HingeEmbeddingCriterion,
    KLDCriterion, L1Cost, MarginRankingCriterion, MultiCriterion,
    MultiLabelMarginCriterion, MultiMarginCriterion, SoftmaxWithCriterion,
)
from bigdl_tpu.nn.init_methods import (
    InitializationMethod, Zeros, Ones, ConstInitMethod, RandomUniform,
    RandomNormal, Xavier, MsraFiller, BilinearFiller,
)
from bigdl_tpu.nn.layers_extra import (
    Bilinear, GaussianDropout, GaussianNoise, HardShrink, HardSigmoid,
    SoftShrink, TanhShrink,
    Cosine, CosineDistance, DotProduct, Euclidean, GaussianSampler,
    GradientReversal, Index, L1Penalty, LogSigmoid, Masking, Negative,
    NarrowTable, PairwiseDistance, Replicate, RReLU, Scale, SelectTable,
    SoftMin, SpatialDilatedConvolution, SpatialUpSamplingBilinear,
    SpatialUpSamplingNearest, SpatialZeroPadding, TemporalConvolution,
    Threshold, VolumetricAveragePooling, VolumetricConvolution,
    VolumetricMaxPooling,
)
from bigdl_tpu.nn.layers_more import (
    Pack, Tile, Reverse, InferReshape, BifurcateSplitTable, MixtureTable,
    MaskedSelect, DenseToSparse, SReLU, Maxout, TemporalMaxPooling,
    TemporalAveragePooling, VolumetricZeroPadding,
    UpSampling1D, UpSampling3D, Cropping2D, Cropping3D,
    VolumetricFullConvolution, LocallyConnected1D, LocallyConnected2D,
    SpatialShareConvolution, SpatialSeparableConvolution,
    SpatialDropout1D, SpatialDropout2D, SpatialDropout3D,
    SpatialWithinChannelLRN, SpatialSubtractiveNormalization,
    SpatialDivisiveNormalization, SpatialContrastiveNormalization,
    NegativeEntropyPenalty, SpatialConvolutionMap,
)
from bigdl_tpu.nn.criterion_more import (
    L1HingeEmbeddingCriterion, PoissonCriterion,
    MeanAbsolutePercentageCriterion, MeanSquaredLogarithmicCriterion,
    KullbackLeiblerDivergenceCriterion, CategoricalCrossEntropy,
    TimeDistributedMaskCriterion,
)
from bigdl_tpu.nn.beam_search import SequenceBeamSearch, beam_search
from bigdl_tpu.nn.sparse import SparseLinear, SparseJoinTable, LookupTableSparse
from bigdl_tpu.nn.quantized import (
    QuantizedLinear, QuantizedSpatialConvolution, Quantizer,
)

Module = AbstractModule  # reference alias: ``Module.load`` etc.
