"""AbstractModule — the core layer contract.

Reference role (UNVERIFIED, SURVEY.md §0):
``.../bigdl/nn/abstractnn/AbstractModule.scala`` — ``forward`` →
``updateOutput``, ``backward`` → ``updateGradInput`` + ``accGradParameters``,
``parameters()``, ``zeroGradParameters``, ``training()/evaluate()``; the north
star requires ``Module.forward`` call sites to stay source-unchanged.

TPU-native redesign — the central architectural decision of this framework:

* Every module is a **pure function pair**: ``init_params(rng) -> params``
  (a pytree of jax arrays) and
  ``apply(params, input, state, training, rng) -> (output, new_state)``.
  ``state`` carries non-learned buffers (BatchNorm running stats, RNN
  carry defaults); ``rng`` feeds stochastic layers (Dropout). ``apply`` is
  referentially transparent, so one ``jax.jit`` traces the whole model and
  XLA fuses it end-to-end — this replaces the reference's per-layer virtual
  dispatch into MKL JNI.

* The BigDL **stateful facade** (``forward``/``backward``/``parameters``/
  ``zero_grad_parameters``) is a thin shell over the pure core: the module
  object owns a ``params`` pytree, a ``grad_params`` accumulator and a
  ``state`` pytree, and ``backward`` is ``jax.vjp`` of ``apply``. Model-zoo
  code and per-layer parity tests use the facade; optimizers compile the
  pure core directly and never touch the facade in the hot loop.

* Mutation-looking reference semantics (in-place ReLU, shared weights,
  gradient accumulation across backward calls) are reproduced at the facade
  level only; under jit everything is functional, which deletes the
  reference's thread-safety sharp edges (SURVEY.md §5.2) by construction.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_instance_counter = itertools.count()


def _unwrap_activity(x: Any) -> Any:
    """Tensor facade / numpy → jax arrays, recursively through Tables/lists."""
    import jax.numpy as jnp

    from bigdl_tpu.tensor import Tensor
    from bigdl_tpu.utils.table import Table

    if isinstance(x, Tensor):
        return x.data
    if isinstance(x, Table):
        return [_unwrap_activity(v) for v in x.to_list()]
    if isinstance(x, (list, tuple)):
        return [_unwrap_activity(v) for v in x]
    if isinstance(x, (np.ndarray, float, int)):
        return jnp.asarray(x)
    return x


class AbstractModule:
    """Base class for every layer, container and graph."""

    def __init__(self) -> None:
        self.name: str = f"{type(self).__name__}{next(_instance_counter)}"
        self.train_mode: bool = True
        # facade storage
        self.params: Optional[Dict[str, Any]] = None
        self.grad_params: Optional[Dict[str, Any]] = None
        self.state: Dict[str, Any] = {}
        self.output: Any = None
        self.grad_input: Any = None
        self._facade_rng_count = 0

    # ------------------------------------------------------------------
    # pure core — subclasses override these three
    # ------------------------------------------------------------------

    def init_params(self, rng) -> Dict[str, Any]:
        """Build this module's learnable parameter pytree."""
        return {}

    def init_state(self) -> Dict[str, Any]:
        """Build this module's non-learnable buffer pytree."""
        return {}

    def apply(self, params, input, state=None, training: bool = False, rng=None):
        """Pure forward: returns ``(output, new_state)``."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # naming / modes
    # ------------------------------------------------------------------

    def set_name(self, name: str) -> "AbstractModule":
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name

    def training(self) -> "AbstractModule":
        self.train_mode = True
        for m in self.sub_modules():
            m.training()
        return self

    def evaluate(self, dataset=None, methods=None, batch_size: int = 32):
        """No args: switch to eval mode (reference ``evaluate()``).
        With a dataset + ValidationMethods: run batched evaluation and
        return the results (reference ``evaluate(rdd, methods)`` →
        ``Evaluator`` path, SURVEY.md §3.3)."""
        if dataset is not None:
            from bigdl_tpu.optim.evaluator import Evaluator

            return Evaluator(self).test(dataset, methods or [], batch_size)
        self.train_mode = False
        for m in self.sub_modules():
            m.evaluate()
        return self

    def is_training(self) -> bool:
        return self.train_mode

    def sub_modules(self) -> List["AbstractModule"]:
        return []

    # ------------------------------------------------------------------
    # facade: parameter materialization
    # ------------------------------------------------------------------

    def _materialize_params(self) -> None:
        """Weights/state only — no gradient buffers (save-path half)."""
        if self.params is None:
            from bigdl_tpu.utils.random_gen import RNG

            self.params = self.init_params(RNG.next_key())
            self.state = self.init_state()

    def _ensure_params(self) -> None:
        self._materialize_params()
        if self.grad_params is None:
            import jax

            self.grad_params = jax.tree_util.tree_map(
                lambda p: np.zeros_like(np.asarray(p)), self.params
            )

    def reset(self, rng=None) -> "AbstractModule":
        """Re-initialize parameters (reference ``reset()``)."""
        from bigdl_tpu.utils.random_gen import RNG

        self.params = self.init_params(rng if rng is not None else RNG.next_key())
        self.state = self.init_state()
        self.grad_params = None
        self._ensure_params()
        return self

    def parameters(self) -> Tuple[List[Any], List[Any]]:
        """(weights, gradWeights) as flat leaf lists, reference-style."""
        import jax

        self._ensure_params()
        ws = jax.tree_util.tree_leaves(self.params)
        gs = jax.tree_util.tree_leaves(self.grad_params)
        return ws, gs

    def get_weights(self) -> List[Any]:
        """Weights as a list of numpy arrays (pyspark ``get_weights``).
        Materializes weights only — no gradient buffers."""
        import jax
        import numpy as _np

        self._materialize_params()
        return [_np.asarray(w) for w in jax.tree_util.tree_leaves(self.params)]

    def set_weights(self, weights) -> "AbstractModule":
        """Assign weights from a list in ``get_weights`` order (pyspark
        ``set_weights``)."""
        import jax
        import numpy as _np

        self._materialize_params()
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        if len(weights) != len(leaves):
            raise ValueError(
                f"set_weights got {len(weights)} arrays for "
                f"{len(leaves)} parameter leaves")
        new = []
        for old, w in zip(leaves, weights):
            w = _np.asarray(w)
            if tuple(w.shape) != tuple(old.shape):
                raise ValueError(
                    f"set_weights shape mismatch: {w.shape} vs {old.shape}")
            new.append(w.astype(old.dtype))
        self.params = jax.tree_util.tree_unflatten(treedef, new)
        return self

    # -- freezing (reference Graph.freeze/unfreeze: transfer learning) -----
    # tri-state per module: None = inherit from parent, True/False explicit
    # (an explicit False OVERRIDES a frozen ancestor, so the classic
    # `model.freeze(); model.unfreeze("head")` flow trains the head)

    def freeze(self, *names: str) -> "AbstractModule":
        """Stop training this module (no names) or the named sub-modules:
        their gradients are zeroed and their weights restored bit-identical
        after every optimizer update. (Optimizer slots of frozen leaves
        still step with zero gradients — e.g. momentum decays toward 0 —
        only the WEIGHTS are guaranteed untouched.)"""
        self._set_frozen(True, names)
        return self

    def unfreeze(self, *names: str) -> "AbstractModule":
        """With names: explicitly unfreeze those sub-modules (overriding
        frozen ancestors). Without names: clear EVERY freeze flag in the
        whole tree."""
        if not names:
            def clear(mod):
                mod._frozen = None
                for sub in mod.sub_modules() or []:
                    clear(sub)

            clear(self)
            return self
        self._set_frozen(False, names)
        return self

    def _set_frozen(self, value, names) -> None:
        if not names:
            self._frozen = value
            return
        found = set()

        def walk(mod):
            if mod.name in names:
                mod._frozen = value
                found.add(mod.name)
            for sub in mod.sub_modules() or []:
                walk(sub)

        walk(self)
        missing = set(names) - found
        if missing:
            raise ValueError(f"freeze/unfreeze: no sub-module named "
                             f"{sorted(missing)}")

    def frozen_flag(self):
        """None (inherit) / True / False — see freeze()."""
        return getattr(self, "_frozen", None)

    def is_frozen(self) -> bool:
        return bool(getattr(self, "_frozen", None))

    def get_parameters(self):
        """One flattened (weight, grad) vector pair.

        Reference: ``Module.getParameters`` compacts all parameters into a
        single contiguous tensor — the representation ``AllReduceParameter``
        shards. Used by tests and the partitioned optimizer path.
        """
        import jax.numpy as jnp

        ws, gs = self.parameters()
        if not ws:
            return jnp.zeros((0,)), jnp.zeros((0,))
        flat_w = jnp.concatenate([jnp.ravel(w) for w in ws])
        flat_g = jnp.concatenate([jnp.ravel(jnp.asarray(g)) for g in gs])
        return flat_w, flat_g

    def zero_grad_parameters(self) -> None:
        import jax

        self._ensure_params()
        self.grad_params = jax.tree_util.tree_map(
            lambda g: np.zeros_like(np.asarray(g)), self.grad_params
        )

    def n_parameters(self) -> int:
        ws, _ = self.parameters()
        return int(sum(np.prod(np.asarray(w).shape) for w in ws))

    # ------------------------------------------------------------------
    # facade: forward / backward
    # ------------------------------------------------------------------

    def _facade_rng(self):
        from bigdl_tpu.utils.random_gen import RNG

        self._facade_rng_count += 1
        return RNG.next_key()

    def forward(self, input: Any) -> Any:
        self._ensure_params()
        x = _unwrap_activity(input)
        rng = self._facade_rng() if self.train_mode else None
        out, new_state = self.apply(
            self.params, x, self.state, training=self.train_mode, rng=rng
        )
        self.state = new_state
        self.output = out
        return out

    __call__ = forward

    # reference aliases
    def update_output(self, input: Any) -> Any:
        return self.forward(input)

    def backward(self, input: Any, grad_output: Any) -> Any:
        """gradInput = d(loss)/d(input); also ACCUMULATES param grads
        (reference ``updateGradInput`` + ``accGradParameters`` in one vjp)."""
        import jax

        self._ensure_params()
        x = _unwrap_activity(input)
        g = _unwrap_activity(grad_output)
        rng = None  # deterministic backward against the last forward

        def f(p, xx):
            return self.apply(p, xx, self.state, training=self.train_mode, rng=rng)

        (out, _new_state), vjp_fn = jax.vjp(f, self.params, x, has_aux=False)
        # apply returns (out, state); vjp over the tuple needs a zero cotangent
        # for the state leg.
        zero_state = jax.tree_util.tree_map(lambda s: np.zeros_like(np.asarray(s)), _new_state)
        gp, gx = vjp_fn((g, zero_state))
        self.grad_params = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) + np.asarray(b), self.grad_params, gp
        )
        self.grad_input = gx
        return gx

    def update_grad_input(self, input: Any, grad_output: Any) -> Any:
        return self.backward(input, grad_output)

    def acc_grad_parameters(self, input: Any, grad_output: Any) -> None:
        self.backward(input, grad_output)

    # ------------------------------------------------------------------
    # persistence (reference Module.save / Module.load via utils.File)
    # ------------------------------------------------------------------

    def save(self, path: str, over_write: bool = False) -> "AbstractModule":
        from bigdl_tpu.utils.file_io import File

        self._ensure_params()
        File.save(
            {"module": self, "params": self.params, "state": self.state},
            path,
            over_write=over_write,
        )
        return self

    def save_module(self, path: str, over_write: bool = False) -> "AbstractModule":
        """Versioned structured snapshot (reference ``saveModule`` — the
        protobuf path, vs ``save``'s legacy serialization)."""
        from bigdl_tpu.utils.serializer import save_module

        save_module(self, path, over_write=over_write)
        return self

    @staticmethod
    def load_module(path: str) -> "AbstractModule":
        """Load a :meth:`save_module` snapshot (reference ``loadModule``)."""
        from bigdl_tpu.utils.serializer import load_module

        return load_module(path)

    @staticmethod
    def load(path: str) -> "AbstractModule":
        from bigdl_tpu.utils.file_io import File

        blob = File.load(path)
        m: AbstractModule = blob["module"]
        m.params = blob["params"]
        m.state = blob["state"]
        m.grad_params = None
        m._ensure_params()
        return m

    @staticmethod
    def load_caffe_model(def_path: str, model_path=None, match_all=True):
        """Reference ``Module.loadCaffeModel(defPath, modelPath)``."""
        from bigdl_tpu.utils.caffe_loader import load_caffe

        return load_caffe(def_path, model_path, match_all)

    @staticmethod
    def load_tf(path, inputs, outputs):
        """Reference ``Module.loadTF(path, inputs, outputs)``."""
        from bigdl_tpu.utils.tf_loader import load_tf

        return load_tf(path, inputs, outputs)

    @staticmethod
    def load_keras(json_path: str = None, hdf5_path: str = None):
        """Reference pyspark ``Model.load_keras(json_path, hdf5_path)``:
        import a Keras-1.2 architecture (+ HDF5 weights) as a native
        model (``utils/keras_loader.py``)."""
        from bigdl_tpu.utils.keras_loader import load_keras

        return load_keras(json_path, hdf5_path)

    def __getstate__(self):
        d = dict(self.__dict__)
        # grads and cached activations are not part of a snapshot
        d["grad_params"] = None
        d["output"] = None
        d["grad_input"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)

    # ------------------------------------------------------------------
    # evaluation / prediction conveniences (full versions in optim/)
    # ------------------------------------------------------------------

    def predict(self, inputs, batch_size: int = 32) -> Any:
        """Batched prediction in evaluate mode (reference
        ``model.predict`` → Predictor path)."""
        from bigdl_tpu.optim.evaluator import Predictor

        was_training = self.train_mode
        try:
            return Predictor(self).predict(inputs, batch_size)
        finally:
            if was_training:
                self.training()

    def predict_image(self, image_frame, output_layer=None,
                      share_buffer: bool = False,
                      batch_per_partition: int = 4,
                      predict_key: str = "predict",
                      feature_key: str = "floats"):
        """Reference pyspark ``model.predict_image(image_frame, ...)``
        (``Predictor.predictImage``): forward every ImageFeature's tensor
        (``MatToTensor`` output under ``feature_key``) through the model
        in batches and attach each output to its feature under
        ``predict_key``. Returns the same frame. ``share_buffer`` is
        accepted for source compatibility and ignored (XLA owns buffers);
        ``output_layer`` selection of intermediate nodes is not supported
        — forward the sub-graph instead."""
        if output_layer is not None:
            raise NotImplementedError(
                "predict_image(output_layer=...) is not supported — build "
                "a Graph ending at that node and predict with it")
        feats = image_frame.features
        missing = [i for i, f in enumerate(feats) if feature_key not in f]
        if missing:
            raise ValueError(
                f"predict_image: features {missing[:5]} have no "
                f"{feature_key!r} tensor — run MatToTensor (or pass "
                "feature_key=) first")
        import numpy as _np

        x = _np.stack([_np.asarray(f[feature_key], _np.float32)
                       for f in feats])
        # one batching/eval-mode path for all prediction (Predictor
        # handles multi-output models and ragged batch tails)
        out = self.predict(x, batch_size=max(1, int(batch_per_partition)))
        if isinstance(out, (list, tuple)):   # multi-output Graph
            for j, f in enumerate(feats):
                f[predict_key] = [_np.asarray(o)[j] for o in out]
        else:
            out = _np.asarray(out)
            for j, f in enumerate(feats):
                f[predict_key] = out[j]
        return image_frame

    def to_ir(self, input_shape, dtype=None, training: bool = False):
        """Lower this module to its jaxpr IR for the given input shape.

        The reference converted module graphs to an intermediate
        representation once per engine (``utils/intermediate/IRGraph`` →
        ``DnnGraph`` under ``EngineType.MklDnn``); here the analogous
        lowering is Module graph → jaxpr → XLA HLO, and this inspector
        returns the traced jaxpr (str() it for a readable dump).
        """
        import jax
        import jax.numpy as jnp

        self._materialize_params()
        x = jax.ShapeDtypeStruct(tuple(input_shape), dtype or jnp.float32)
        # training mode traces with a key so rng-dependent layers (Dropout)
        # appear in the IR instead of silently no-op'ing
        rng = jax.random.PRNGKey(0) if training else None

        def fn(p, xx):
            out, _ = self.apply(p, xx, self.state, training=training,
                                rng=rng)
            return out

        return jax.make_jaxpr(fn)(self.params, x)

    def quantize(self, scheme: str = "dynamic") -> "AbstractModule":
        """int8-quantize this trained model for inference (reference
        ``module.quantize()`` → ``nn/quantized`` path).
        ``scheme="weight_only"`` selects the bf16-activation serving mode
        (see ``QuantizedLinear``)."""
        from bigdl_tpu.nn.quantized import Quantizer

        return Quantizer.quantize(self, scheme=scheme)

    def predict_class(self, inputs, batch_size: int = 32):
        """1-based predicted classes (reference ``predictClass``)."""
        from bigdl_tpu.optim.evaluator import Predictor

        was_training = self.train_mode
        try:
            return Predictor(self).predict_class(inputs, batch_size)
        finally:
            if was_training:
                self.training()

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name})"


class TensorModule(AbstractModule):
    """Marker base for modules whose Activity is a single tensor."""


class Identity(TensorModule):
    def apply(self, params, input, state=None, training=False, rng=None):
        return input, state


class Echo(TensorModule):
    """Debug layer: prints shape on forward (reference ``Echo``)."""

    def apply(self, params, input, state=None, training=False, rng=None):
        print(f"[Echo {self.name}] shape={getattr(input, 'shape', None)}")
        return input, state
