"""Keras-style API: Sequential/Model with shape inference.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/nn/keras/`` — a Keras-1.2
flavored layer set (``Dense``, ``Convolution2D``, ``MaxPooling2D``, …) with
``InferShape`` propagating shapes so only the FIRST layer declares
``input_shape``.

TPU-native redesign: each Keras layer is a thin shape-aware builder over the
core ``bigdl_tpu.nn`` modules. Shape inference runs EAGERLY at ``add()`` /
call time (every layer knows its output shape from its input shape), so the
underlying core module graph exists immediately and ``jit`` traces one flat
program — no deferred-build machinery at apply time.

Shapes exclude the batch dim; images are CHW (matching the core NCHW conv).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from bigdl_tpu.nn import activations as _act
from bigdl_tpu.nn import containers as _containers
from bigdl_tpu.nn.module import AbstractModule

Shape = Tuple[int, ...]

_ACTIVATIONS = {
    "relu": _act.ReLU, "tanh": _act.Tanh, "sigmoid": _act.Sigmoid,
    "softmax": _act.SoftMax, "log_softmax": _act.LogSoftMax,
    "elu": _act.ELU, "softplus": _act.SoftPlus, "softsign": _act.SoftSign,
    "gelu": _act.GELU, "linear": None, None: None,
}


class KerasLayer(AbstractModule):
    """Base: a shape-aware builder producing a core module in ``build``."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None) -> None:
        super().__init__()
        self.input_shape: Optional[Shape] = (
            tuple(input_shape) if input_shape is not None else None
        )
        self.output_shape: Optional[Shape] = None
        self._core: Optional[AbstractModule] = None

    # subclass contract ----------------------------------------------------

    def build_core(self, input_shape: Shape) -> AbstractModule:
        raise NotImplementedError

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        raise NotImplementedError

    # plumbing -------------------------------------------------------------

    def build(self, input_shape: Shape) -> "KerasLayer":
        self.input_shape = tuple(input_shape)
        self._core = self.build_core(self.input_shape)
        self.output_shape = self.compute_output_shape(self.input_shape)
        return self

    def get_output_shape(self) -> Shape:
        assert self.output_shape is not None, f"{self} is not built yet"
        return self.output_shape

    def init_params(self, rng):
        assert self._core is not None, f"{self} is not built yet"
        return self._core.init_params(rng)

    def init_state(self):
        return self._core.init_state() if self._core is not None else {}

    def apply(self, params, input, state=None, training=False, rng=None):
        assert self._core is not None, f"{self} is not built yet"
        return self._core.apply(params, input, state, training=training, rng=rng)

    def sub_modules(self):
        return [self._core] if self._core is not None else []

    # functional (Model) API: layer(node) builds from the node's shape
    def __call__(self, node):  # type: ignore[override]
        if isinstance(node, KerasNode):
            self.build(node.shape)
            return KerasNode(self.get_output_shape(), self, [node])
        return self.forward(node)


class KerasNode:
    """A symbolic tensor in the functional API: (shape, producing layer)."""

    def __init__(self, shape: Shape, layer: Optional[KerasLayer],
                 inbound: Sequence["KerasNode"]) -> None:
        self.shape = tuple(shape)
        self.layer = layer
        self.inbound = list(inbound)


def Input(shape: Sequence[int]) -> KerasNode:
    """Entry point of the functional API (batch dim excluded)."""
    return KerasNode(tuple(shape), None, [])


def _maybe_activation(core: AbstractModule, activation) -> AbstractModule:
    if activation is None or activation == "linear":
        return core
    act = _ACTIVATIONS[activation]() if isinstance(activation, str) else activation
    return _containers.Sequential().add(core).add(act)


class Dense(KerasLayer):
    def __init__(self, output_dim: int, activation=None, bias: bool = True,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.linear import Linear

        return _maybe_activation(
            Linear(input_shape[-1], self.output_dim, with_bias=self.bias),
            self.activation,
        )

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, input_shape=None) -> None:
        super().__init__(input_shape)
        self.activation = activation

    def build_core(self, input_shape):
        return _ACTIVATIONS[self.activation]()

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape=None) -> None:
        super().__init__(input_shape)
        self.p = p

    def build_core(self, input_shape):
        from bigdl_tpu.nn.misc import Dropout as CoreDropout

        return CoreDropout(self.p)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class Flatten(KerasLayer):
    def build_core(self, input_shape):
        import numpy as np

        from bigdl_tpu.nn.shape_ops import Reshape

        return Reshape([int(np.prod(input_shape))], batch_mode=True)

    def compute_output_shape(self, input_shape):
        import numpy as np

        return (int(np.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int], input_shape=None) -> None:
        super().__init__(input_shape)
        self.target_shape = tuple(target_shape)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.shape_ops import Reshape as CoreReshape

        return CoreReshape(list(self.target_shape), batch_mode=True)

    def compute_output_shape(self, input_shape):
        return self.target_shape


class Convolution2D(KerasLayer):
    """CHW input; ``border_mode``: 'valid' | 'same' (Keras-1.2 names)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 subsample: Tuple[int, int] = (1, 1),
                 border_mode: str = "valid", activation=None,
                 bias: bool = True, input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.subsample = tuple(subsample)
        self.border_mode = border_mode
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.conv import SpatialConvolution

        pad = -1 if self.border_mode == "same" else 0
        return _maybe_activation(
            SpatialConvolution(
                input_shape[0], self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0], pad, pad,
                with_bias=self.bias,
            ),
            self.activation,
        )

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh = -(-h // sh)
            ow = -(-w // sw)
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class _Pooling2D(KerasLayer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides is not None else self.pool_size
        self.border_mode = border_mode

    def _core_cls(self):
        raise NotImplementedError

    def build_core(self, input_shape):
        pad = -1 if self.border_mode == "same" else 0
        return self._core_cls()(
            self.pool_size[1], self.pool_size[0],
            self.strides[1], self.strides[0], pad, pad,
        )

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        if self.border_mode == "same":
            return (c, -(-h // sh), -(-w // sw))
        return (c, (h - ph) // sh + 1, (w - pw) // sw + 1)


class MaxPooling2D(_Pooling2D):
    def _core_cls(self):
        from bigdl_tpu.nn.pooling import SpatialMaxPooling

        return SpatialMaxPooling


class AveragePooling2D(_Pooling2D):
    def _core_cls(self):
        from bigdl_tpu.nn.pooling import SpatialAveragePooling

        return SpatialAveragePooling


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.epsilon = epsilon
        self.momentum = momentum

    def build_core(self, input_shape):
        from bigdl_tpu.nn import normalization as _norm

        if len(input_shape) == 3:  # CHW feature maps
            return _norm.SpatialBatchNormalization(
                input_shape[0], eps=self.epsilon, momentum=1 - self.momentum)
        return _norm.BatchNormalization(
            input_shape[-1], eps=self.epsilon, momentum=1 - self.momentum)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class _ShiftIndices(AbstractModule):
    """Keras token ids are 0-based; the core LookupTable is 1-based
    (reference convention) — shift by +1, preserving the integer dtype."""

    def apply(self, params, input, state=None, training=False, rng=None):
        return input + 1, state


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int, input_shape=None) -> None:
        super().__init__(input_shape)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build_core(self, input_shape):
        from bigdl_tpu.nn.misc import LookupTable

        return (_containers.Sequential()
                .add(_ShiftIndices())
                .add(LookupTable(self.input_dim, self.output_dim)))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _KerasRecurrent(KerasLayer):
    def __init__(self, output_dim: int, return_sequences: bool = False,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.return_sequences = return_sequences

    def _cell(self, input_shape):
        """Build the recurrent cell from the FULL (unbatched) input shape —
        vector cells use ``input_shape[-1]``, spatial cells (ConvLSTM2D)
        the channel/spatial dims."""
        raise NotImplementedError

    def build_core(self, input_shape):
        from bigdl_tpu.nn.recurrent import Recurrent
        from bigdl_tpu.nn.shape_ops import Select

        rec = Recurrent().add(self._cell(input_shape))
        if self.return_sequences:
            return rec
        return _containers.Sequential().add(rec).add(Select(2, -1))

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class LSTM(_KerasRecurrent):
    def _cell(self, input_shape):
        from bigdl_tpu.nn.recurrent import LSTM as CoreLSTM

        return CoreLSTM(input_shape[-1], self.output_dim)


class GRU(_KerasRecurrent):
    def _cell(self, input_shape):
        from bigdl_tpu.nn.recurrent import GRU as CoreGRU

        # keras1 GRU math (reset BEFORE the candidate matmul) — this is
        # the keras-compat layer, and load_keras routes GRU weights here
        return CoreGRU(input_shape[-1], self.output_dim,
                       reset_after=False)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), input_shape=None) -> None:
        super().__init__(input_shape)
        self.padding = tuple(padding)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import SpatialZeroPadding

        ph, pw = self.padding
        return SpatialZeroPadding(pw, pw, ph, ph)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        ph, pw = self.padding
        return (c, h + 2 * ph, w + 2 * pw)


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), input_shape=None) -> None:
        super().__init__(input_shape)
        assert size[0] == size[1], "UpSampling2D wants square scale"
        self.size = tuple(size)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import SpatialUpSamplingNearest

        return SpatialUpSamplingNearest(self.size[0])

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h * self.size[0], w * self.size[1])


class GlobalAveragePooling2D(KerasLayer):
    def build_core(self, input_shape):
        from bigdl_tpu.nn.pooling import SpatialAveragePooling
        from bigdl_tpu.nn.shape_ops import Reshape

        pool = SpatialAveragePooling(1, 1, 1, 1, global_pooling=True)
        return _containers.Sequential().add(pool).add(
            Reshape([input_shape[0]], batch_mode=True))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class Merge(KerasLayer):
    """Combine a list of inputs: ``mode`` ∈ sum|mul|max|concat (Keras-1.2
    ``Merge``). ``concat_axis`` follows Keras semantics — it indexes the
    BATCHED tensor (axis 0 = batch, which is invalid to concat; -1 = last).
    """

    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        assert mode in ("sum", "mul", "max", "concat")
        if mode == "concat" and concat_axis == 0:
            raise ValueError("cannot concat along the batch axis")
        self.mode = mode
        self.concat_axis = concat_axis
        self._n_inputs = 2  # refined when called with functional nodes

    def build_core(self, input_shape):
        from bigdl_tpu.nn import shape_ops as S

        if self.mode == "sum":
            return S.CAddTable()
        if self.mode == "mul":
            return S.CMulTable()
        if self.mode == "max":
            return S.CMaxTable()
        # concat: JoinTable's n_input_dims handles the implicit batch dim,
        # so a batched-tensor axis k maps to 1-based non-batch dim k
        ax = self.concat_axis
        dim = len(self.input_shape) if ax == -1 else ax
        return S.JoinTable(dim, len(self.input_shape))

    def compute_output_shape(self, input_shape):
        if self.mode != "concat":
            return tuple(input_shape)
        shape = list(input_shape)
        ax = self.concat_axis if self.concat_axis != -1 else len(shape)
        shape[ax - 1] *= self._n_inputs  # batchless index of batched axis ax
        return tuple(shape)

    def __call__(self, nodes):  # type: ignore[override]
        if isinstance(nodes, (list, tuple)) and nodes and isinstance(
                nodes[0], KerasNode):
            self._n_inputs = len(nodes)
            self.build(nodes[0].shape)
            return KerasNode(self.get_output_shape(), self, list(nodes))
        return super().__call__(nodes)


class Highway(KerasLayer):
    """Keras-1.2 Highway layer: ``t·h(x) + (1−t)·x`` with learned transform
    and carry gates."""

    def __init__(self, activation="relu", input_shape=None) -> None:
        super().__init__(input_shape)
        self.activation = activation

    def build_core(self, input_shape):
        from bigdl_tpu.nn.linear import Linear
        from bigdl_tpu.nn.module import TensorModule

        d = input_shape[-1]
        act = _ACTIVATIONS[self.activation]

        class _HighwayCore(TensorModule):
            def __init__(self, d_):
                super().__init__()
                self.h = Linear(d_, d_)
                self.t = Linear(d_, d_)
                self.act = act() if act else None

            def sub_modules(self):
                return [self.h, self.t]

            def init_params(self, rng):
                import jax

                k1, k2 = jax.random.split(rng)
                return {f"0:{self.h.name}": self.h.init_params(k1),
                        f"1:{self.t.name}": self.t.init_params(k2)}

            def apply(self, params, input, state=None, training=False,
                      rng=None):
                import jax

                h, _ = self.h.apply(params[f"0:{self.h.name}"], input)
                if self.act is not None:
                    h, _ = self.act.apply({}, h)
                t, _ = self.t.apply(params[f"1:{self.t.name}"], input)
                t = jax.nn.sigmoid(t)
                return t * h + (1 - t) * input, state

        return _HighwayCore(d)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class Sequential(KerasLayer):
    """Keras-style Sequential: the first layer carries ``input_shape``;
    every later layer infers its shape at ``add`` time."""

    def __init__(self) -> None:
        super().__init__()
        self.layers = []
        self._seq = _containers.Sequential()
        self._core = self._seq
        self._cur: Optional[Shape] = None

    def add(self, layer: KerasLayer) -> "Sequential":
        if self._cur is None:
            assert layer.input_shape is not None, (
                "first layer needs input_shape=..."
            )
            self._cur = layer.input_shape
            self.input_shape = layer.input_shape
        layer.build(self._cur)
        self._cur = layer.get_output_shape()
        self.output_shape = self._cur
        self.layers.append(layer)
        self._seq.add(layer)
        return self

    def build_core(self, input_shape):
        return self._seq

    def compute_output_shape(self, input_shape):
        return self._cur

    def get_output_shape(self) -> Shape:
        assert self._cur is not None, "empty keras Sequential"
        return self._cur


class Model(KerasLayer):
    """Functional API: ``Model(input=node(s), output=node)`` assembles the
    core ``Graph`` from the symbolic KerasNode DAG."""

    def __init__(self, input, output) -> None:
        super().__init__()
        from bigdl_tpu.nn.graph import Graph
        from bigdl_tpu.nn.graph import Input as GraphInput

        ins = input if isinstance(input, (list, tuple)) else [input]
        node_map = {}

        def lower(kn: KerasNode):
            nid = id(kn)
            if nid in node_map:
                return node_map[nid]
            if kn.layer is None:
                gn = GraphInput()
            else:
                gn = kn.layer.inputs(*[lower(p) for p in kn.inbound])
            node_map[nid] = gn
            return gn

        outs = output if isinstance(output, (list, tuple)) else [output]
        g_outs = [lower(o) for o in outs]
        g_ins = [node_map[id(i)] for i in ins]
        self._core = Graph(g_ins if len(g_ins) > 1 else g_ins[0],
                           g_outs if len(g_outs) > 1 else g_outs[0])
        self.input_shape = tuple(ins[0].shape)
        self.output_shape = tuple(outs[0].shape)

    def build_core(self, input_shape):
        return self._core

    def compute_output_shape(self, input_shape):
        return self.output_shape


# ---------------------------------------------------------------------------
# breadth batch 2 (reference nn/keras layer inventory)
# ---------------------------------------------------------------------------

class SimpleRNN(_KerasRecurrent):
    def _cell(self, input_shape):
        from bigdl_tpu.nn.recurrent import RnnCell

        return RnnCell(input_shape[-1], self.output_dim)


class Bidirectional(KerasLayer):
    """Wrap a keras recurrent layer spec in a BiRecurrent (reference
    ``nn/keras/Bidirectional.scala``); ``merge_mode`` "concat" | "sum"."""

    def __init__(self, layer: _KerasRecurrent, merge_mode: str = "concat",
                 input_shape=None) -> None:
        super().__init__(input_shape or layer.input_shape)
        assert layer.return_sequences, (
            "Bidirectional requires return_sequences=True (reference rule)")
        self.layer = layer
        self.merge_mode = merge_mode

    def build_core(self, input_shape):
        from bigdl_tpu.nn.recurrent import BiRecurrent

        merge = "concat" if self.merge_mode == "concat" else "add"
        return BiRecurrent(merge=merge).add(self.layer._cell(input_shape))

    def compute_output_shape(self, input_shape):
        h = self.layer.output_dim
        if self.merge_mode == "concat":
            h *= 2
        return (input_shape[0], h)


class TimeDistributed(KerasLayer):
    """Apply an inner keras layer to every timestep (reference
    ``nn/keras/TimeDistributed.scala``)."""

    def __init__(self, layer: KerasLayer, input_shape=None) -> None:
        super().__init__(input_shape)
        self.layer = layer

    def build_core(self, input_shape):
        from bigdl_tpu.nn.recurrent import TimeDistributed as CoreTD

        self.layer.build(tuple(input_shape[1:]))
        return CoreTD(self.layer._core)

    def compute_output_shape(self, input_shape):
        inner = self.layer.compute_output_shape(tuple(input_shape[1:]))
        return (input_shape[0],) + tuple(inner)


class Convolution1D(KerasLayer):
    """Temporal convolution over (steps, input_dim) input (reference
    ``nn/keras/Convolution1D.scala``); ``border_mode`` "valid" | "same"."""

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 bias: bool = True, input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample_length
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import TemporalConvolution
        from bigdl_tpu.nn.shape_ops import Padding

        core = TemporalConvolution(input_shape[-1], self.nb_filter,
                                   self.filter_length, self.subsample)
        if self.border_mode == "same":
            pad = self.filter_length - 1
            seq = _containers.Sequential()
            # symmetric time padding before the valid conv
            seq.add(Padding(1, -(pad // 2), 2))
            seq.add(Padding(1, pad - pad // 2, 2))
            seq.add(core)
            return _maybe_activation(seq, self.activation)
        return _maybe_activation(core, self.activation)

    def compute_output_shape(self, input_shape):
        t = input_shape[0]
        if self.border_mode == "valid":
            t = (t - self.filter_length) // self.subsample + 1
        else:
            t = (t + self.subsample - 1) // self.subsample
        return (t, self.nb_filter)


class SeparableConvolution2D(KerasLayer):
    """Depthwise-separable conv over NCHW (reference
    ``nn/keras/SeparableConvolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, depth_multiplier: int = 1,
                 border_mode: str = "valid", subsample=(1, 1),
                 bias: bool = True, input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.depth_multiplier = depth_multiplier
        self.border_mode = border_mode
        self.subsample = tuple(subsample)
        self.bias = bias

    def _pads(self):
        if self.border_mode == "same":
            return (self.nb_col // 2, self.nb_row // 2)
        return (0, 0)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import SpatialSeparableConvolution

        pw, ph = self._pads()
        core = SpatialSeparableConvolution(
            input_shape[0], self.nb_filter, self.depth_multiplier,
            self.nb_col, self.nb_row, self.subsample[1], self.subsample[0],
            pw, ph, with_bias=self.bias)
        return _maybe_activation(core, self.activation)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        pw, ph = self._pads()
        oh = (h + 2 * ph - self.nb_row) // self.subsample[0] + 1
        ow = (w + 2 * pw - self.nb_col) // self.subsample[1] + 1
        return (self.nb_filter, oh, ow)


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, bias: bool = True,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample = subsample_length
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import LocallyConnected1D as Core

        core = Core(input_shape[0], input_shape[1], self.nb_filter,
                    self.filter_length, self.subsample, with_bias=self.bias)
        return _maybe_activation(core, self.activation)

    def compute_output_shape(self, input_shape):
        t = (input_shape[0] - self.filter_length) // self.subsample + 1
        return (t, self.nb_filter)


class LocallyConnected2D(KerasLayer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), bias: bool = True,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import LocallyConnected2D as Core

        c, h, w = input_shape
        core = Core(c, w, h, self.nb_filter, self.nb_col, self.nb_row,
                    self.subsample[1], self.subsample[0],
                    with_bias=self.bias)
        return _maybe_activation(core, self.activation)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        oh = (h - self.nb_row) // self.subsample[0] + 1
        ow = (w - self.nb_col) // self.subsample[1] + 1
        return (self.nb_filter, oh, ow)


class Cropping1D(KerasLayer):
    def __init__(self, cropping=(1, 1), input_shape=None) -> None:
        super().__init__(input_shape)
        self.cropping = tuple(cropping)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.shape_ops import Narrow

        lo, hi = self.cropping
        # Narrow's offset is 1-based (reference convention)
        return Narrow(2, lo + 1, input_shape[0] - lo - hi)

    def compute_output_shape(self, input_shape):
        return (input_shape[0] - sum(self.cropping),) + tuple(input_shape[1:])


class Cropping2D(KerasLayer):
    def __init__(self, heightCrop=(0, 0), widthCrop=(0, 0),
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.hc, self.wc = tuple(heightCrop), tuple(widthCrop)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import Cropping2D as Core

        return Core(self.hc, self.wc)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h - sum(self.hc), w - sum(self.wc))


class Cropping3D(KerasLayer):
    def __init__(self, dim1Crop=(0, 0), dim2Crop=(0, 0), dim3Crop=(0, 0),
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.crops = (tuple(dim1Crop), tuple(dim2Crop), tuple(dim3Crop))

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import Cropping3D as Core

        return Core(*self.crops)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        (a, b), (e, f), (g, k) = self.crops
        return (c, d - a - b, h - e - f, w - g - k)


class Permute(KerasLayer):
    """Permute the non-batch dims (1-based dims, reference
    ``nn/keras/Permute.scala``)."""

    def __init__(self, dims: Sequence[int], input_shape=None) -> None:
        super().__init__(input_shape)
        self.dims = tuple(dims)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.module import TensorModule

        perm = self.dims

        class _Permute(TensorModule):
            def apply(self, params, input, state=None, training=False,
                      rng=None):
                import jax.numpy as jnp

                order = (0,) + tuple(p for p in perm)
                return jnp.transpose(input, order), state

        return _Permute()

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    """(features,) → (n, features) (reference ``nn/keras/RepeatVector.scala``)."""

    def __init__(self, n: int, input_shape=None) -> None:
        super().__init__(input_shape)
        self.n = n

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Replicate

        return Replicate(self.n, 1)

    def compute_output_shape(self, input_shape):
        return (self.n,) + tuple(input_shape)


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim: int, nb_feature: int = 4, bias: bool = True,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import Maxout

        return Maxout(input_shape[-1], self.output_dim, self.nb_feature,
                      with_bias=self.bias)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, input_shape=None) -> None:
        super().__init__(input_shape)
        self.theta = theta

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Threshold

        return Threshold(self.theta, 0.0)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class SReLU(KerasLayer):
    def __init__(self, shared_axes=None, input_shape=None) -> None:
        super().__init__(input_shape)
        self.shared_axes = shared_axes

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import SReLU as Core

        return Core(tuple(input_shape), self.shared_axes)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class _IdentityShaped(KerasLayer):
    """Shared base for shape-preserving wrappers."""

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class GaussianNoise(_IdentityShaped):
    def __init__(self, sigma: float, input_shape=None) -> None:
        super().__init__(input_shape)
        self.sigma = sigma

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import GaussianNoise as Core

        return Core(self.sigma)


class GaussianDropout(_IdentityShaped):
    def __init__(self, p: float, input_shape=None) -> None:
        super().__init__(input_shape)
        self.p = p

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import GaussianDropout as Core

        return Core(self.p)


class SpatialDropout1D(_IdentityShaped):
    def __init__(self, p: float = 0.5, input_shape=None) -> None:
        super().__init__(input_shape)
        self.p = p

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import SpatialDropout1D as Core

        return Core(self.p)


class SpatialDropout2D(_IdentityShaped):
    def __init__(self, p: float = 0.5, input_shape=None) -> None:
        super().__init__(input_shape)
        self.p = p

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import SpatialDropout2D as Core

        return Core(self.p)


class Masking(_IdentityShaped):
    def __init__(self, mask_value: float = 0.0, input_shape=None) -> None:
        super().__init__(input_shape)
        self.mask_value = mask_value

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import Masking as Core

        return Core(self.mask_value)


class LeakyReLU(_IdentityShaped):
    def __init__(self, alpha: float = 0.3, input_shape=None) -> None:
        super().__init__(input_shape)
        self.alpha = alpha

    def build_core(self, input_shape):
        from bigdl_tpu.nn.activations import LeakyReLU as Core

        return Core(self.alpha)


class ELU(_IdentityShaped):
    def __init__(self, alpha: float = 1.0, input_shape=None) -> None:
        super().__init__(input_shape)
        self.alpha = alpha

    def build_core(self, input_shape):
        from bigdl_tpu.nn.activations import ELU as Core

        return Core(self.alpha)


# -- round-2 widening: 1D/3D pooling family, padding/upsampling, 3D conv ----
# (reference keras1 API rows — BigDL's keras-1.2 layer set)


class _Pooling1D(KerasLayer):
    """(steps, dim) input; border_mode 'valid' | 'same'."""

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 border_mode: str = "valid", input_shape=None) -> None:
        super().__init__(input_shape)
        if border_mode not in ("valid", "same"):
            raise ValueError(
                f"border_mode must be 'valid' or 'same', got {border_mode!r}")
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length
        self.border_mode = border_mode

    def _core_cls(self):
        raise NotImplementedError

    def build_core(self, input_shape):
        return self._core_cls()(self.pool_length, self.stride,
                                pad_mode=self.border_mode.upper())

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        if self.border_mode == "same":
            return (-(-steps // self.stride), dim)
        return ((steps - self.pool_length) // self.stride + 1, dim)


class MaxPooling1D(_Pooling1D):
    def _core_cls(self):
        from bigdl_tpu.nn.layers_more import TemporalMaxPooling

        return TemporalMaxPooling


class AveragePooling1D(_Pooling1D):
    def _core_cls(self):
        from bigdl_tpu.nn.layers_more import TemporalAveragePooling

        return TemporalAveragePooling


class GlobalMaxPooling1D(KerasLayer):
    """(steps, dim) → (dim,)."""

    def build_core(self, input_shape):
        from bigdl_tpu.nn.misc import Max

        return Max(1, n_input_dims=2)

    def compute_output_shape(self, input_shape):
        return (input_shape[1],)


class GlobalAveragePooling1D(KerasLayer):
    def build_core(self, input_shape):
        from bigdl_tpu.nn.misc import Mean

        return Mean(1, n_input_dims=2)

    def compute_output_shape(self, input_shape):
        return (input_shape[1],)


class GlobalMaxPooling2D(KerasLayer):
    """(C, H, W) → (C,) — one full-window max pool (input shape is known
    at build, so the window IS the image, mirroring
    GlobalAveragePooling2D's single-pass core)."""

    def build_core(self, input_shape):
        from bigdl_tpu.nn.containers import Sequential
        from bigdl_tpu.nn.pooling import SpatialMaxPooling
        from bigdl_tpu.nn.shape_ops import Reshape

        c, h, w = input_shape
        return (Sequential()
                .add(SpatialMaxPooling(w, h))
                .add(Reshape([c], batch_mode=True)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class _Pooling3D(KerasLayer):
    """(C, D, H, W) input; border_mode 'valid' only (reference keras1
    Pooling3D contract)."""

    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode: str = "valid", input_shape=None) -> None:
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError(
                "Pooling3D supports only border_mode='valid' (reference "
                "keras1 contract)")
        self.pool_size = tuple(pool_size)
        self.strides = (tuple(strides) if strides is not None
                        else self.pool_size)

    def _core_cls(self):
        raise NotImplementedError

    def build_core(self, input_shape):
        kt, kh, kw = self.pool_size
        dt, dh, dw = self.strides
        return self._core_cls()(kt, kw, kh, dt, dw, dh)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        (kt, kh, kw), (dt, dh, dw) = self.pool_size, self.strides
        return (c, (d - kt) // dt + 1, (h - kh) // dh + 1,
                (w - kw) // dw + 1)


class MaxPooling3D(_Pooling3D):
    def _core_cls(self):
        from bigdl_tpu.nn.layers_extra import VolumetricMaxPooling

        return VolumetricMaxPooling


class AveragePooling3D(_Pooling3D):
    def _core_cls(self):
        from bigdl_tpu.nn.layers_extra import VolumetricAveragePooling

        return VolumetricAveragePooling


class GlobalMaxPooling3D(KerasLayer):
    """(C, D, H, W) → (C,)."""

    def build_core(self, input_shape):
        from bigdl_tpu.nn.containers import Sequential
        from bigdl_tpu.nn.misc import Max

        return (Sequential()
                .add(Max(4, n_input_dims=4))
                .add(Max(3, n_input_dims=3))
                .add(Max(2, n_input_dims=2)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalAveragePooling3D(KerasLayer):
    def build_core(self, input_shape):
        from bigdl_tpu.nn.containers import Sequential
        from bigdl_tpu.nn.misc import Mean

        return (Sequential()
                .add(Mean(4, n_input_dims=4))
                .add(Mean(3, n_input_dims=3))
                .add(Mean(2, n_input_dims=2)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding1D(KerasLayer):
    """(steps, dim): pad ``padding`` zero timesteps on each side."""

    def __init__(self, padding: int = 1, input_shape=None) -> None:
        super().__init__(input_shape)
        self.padding = padding

    def build_core(self, input_shape):
        from bigdl_tpu.nn.containers import Sequential
        from bigdl_tpu.nn.shape_ops import Padding

        return (Sequential()
                .add(Padding(1, -self.padding, 2))
                .add(Padding(1, self.padding, 2)))

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        return (steps + 2 * self.padding, dim)


class ZeroPadding3D(KerasLayer):
    """(C, D, H, W): symmetric zero padding on the three spatial dims."""

    def __init__(self, padding=(1, 1, 1), input_shape=None) -> None:
        super().__init__(input_shape)
        self.padding = tuple(padding)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import VolumetricZeroPadding

        pt, ph, pw = self.padding
        return VolumetricZeroPadding(pt, ph, pw)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        pt, ph, pw = self.padding
        return (c, d + 2 * pt, h + 2 * ph, w + 2 * pw)


class UpSampling1D(KerasLayer):
    def __init__(self, length: int = 2, input_shape=None) -> None:
        super().__init__(input_shape)
        self.length = length

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import UpSampling1D as Core

        return Core(self.length)

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        return (steps * self.length, dim)


class UpSampling3D(KerasLayer):
    def __init__(self, size=(2, 2, 2), input_shape=None) -> None:
        super().__init__(input_shape)
        self.size = tuple(size)

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import UpSampling3D as Core

        return Core(self.size)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        ft, fh, fw = self.size
        return (c, d * ft, h * fh, w * fw)


class SpatialDropout3D(_IdentityShaped):
    def __init__(self, p: float = 0.5, input_shape=None) -> None:
        super().__init__(input_shape)
        self.p = p

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_more import SpatialDropout3D as Core

        return Core(self.p)


class Convolution3D(KerasLayer):
    """(C, D, H, W) input; border_mode 'valid' only (reference keras1
    Convolution3D contract)."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, subsample=(1, 1, 1),
                 border_mode: str = "valid", activation=None,
                 bias: bool = True, input_shape=None) -> None:
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError(
                "Convolution3D supports only border_mode='valid' "
                "(reference keras1 contract)")
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.subsample = tuple(subsample)
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import VolumetricConvolution

        kt, kh, kw = self.kernel
        dt, dh, dw = self.subsample
        return _maybe_activation(
            VolumetricConvolution(
                input_shape[0], self.nb_filter, kt, kw, kh, dt, dw, dh,
                with_bias=self.bias),
            self.activation)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        (kt, kh, kw), (dt, dh, dw) = self.kernel, self.subsample
        return (self.nb_filter, (d - kt) // dt + 1, (h - kh) // dh + 1,
                (w - kw) // dw + 1)


class Deconvolution2D(KerasLayer):
    """Transposed convolution, (C, H, W) input (reference keras1
    Deconvolution2D over SpatialFullConvolution)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 subsample=(1, 1), activation=None, bias: bool = True,
                 input_shape=None) -> None:
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.subsample = tuple(subsample)
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.conv import SpatialFullConvolution

        return _maybe_activation(
            SpatialFullConvolution(
                input_shape[0], self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0],
                no_bias=not self.bias),
            self.activation)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        sh, sw = self.subsample
        return (self.nb_filter, (h - 1) * sh + self.nb_row,
                (w - 1) * sw + self.nb_col)


class ConvLSTM2D(_KerasRecurrent):
    """Convolutional LSTM over (T, C, H, W) sequences (keras1 ConvLSTM2D
    over the ConvLSTMPeephole core). Positional dialect matches the file's
    Convolution2D convention: ``(nb_filter, nb_row, nb_col)``; the core is
    square-kernel, so nb_row must equal nb_col."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 return_sequences: bool = False,
                 with_peephole: bool = True, input_shape=None) -> None:
        if nb_row != nb_col:
            raise ValueError(
                f"ConvLSTM2D kernel must be square, got {nb_row}x{nb_col}")
        super().__init__(nb_filter, return_sequences, input_shape)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_row
        self.with_peephole = with_peephole

    def _cell(self, input_shape):
        from bigdl_tpu.nn.recurrent import ConvLSTMPeephole

        t, c, h, w = input_shape
        return ConvLSTMPeephole(
            c, self.nb_filter, self.nb_kernel, self.nb_kernel,
            with_peephole=self.with_peephole)

    def compute_output_shape(self, input_shape):
        t, c, h, w = input_shape
        if self.return_sequences:
            return (t, self.nb_filter, h, w)
        return (self.nb_filter, h, w)


class AtrousConvolution2D(KerasLayer):
    """Dilated 2-D convolution, CHW input (keras1 AtrousConvolution2D over
    SpatialDilatedConvolution; border_mode 'valid' only, the keras1
    contract)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 subsample=(1, 1), atrous_rate=(1, 1), activation=None,
                 bias: bool = True, border_mode: str = "valid",
                 input_shape=None) -> None:
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError("AtrousConvolution2D supports only "
                             "border_mode='valid' (keras1 contract)")
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.subsample = tuple(subsample)
        self.atrous_rate = tuple(atrous_rate)
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import SpatialDilatedConvolution

        return _maybe_activation(
            SpatialDilatedConvolution(
                input_shape[0], self.nb_filter, self.nb_col, self.nb_row,
                self.subsample[1], self.subsample[0], 0, 0,
                self.atrous_rate[1], self.atrous_rate[0],
                with_bias=self.bias),
            self.activation)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        (sh, sw), (rh, rw) = self.subsample, self.atrous_rate
        eff_h = (self.nb_row - 1) * rh + 1
        eff_w = (self.nb_col - 1) * rw + 1
        return (self.nb_filter, (h - eff_h) // sh + 1, (w - eff_w) // sw + 1)


class AtrousConvolution1D(KerasLayer):
    """Dilated 1-D convolution over (steps, dim) input (keras1
    AtrousConvolution1D; 'valid' only). Runs as a height-1 dilated 2-D
    conv exactly like the reference's implementation."""

    def __init__(self, nb_filter: int, filter_length: int,
                 subsample_length: int = 1, atrous_rate: int = 1,
                 activation=None, bias: bool = True,
                 border_mode: str = "valid", input_shape=None) -> None:
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError("AtrousConvolution1D supports only "
                             "border_mode='valid' (keras1 contract)")
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.subsample_length = subsample_length
        self.atrous_rate = atrous_rate
        self.activation = activation
        self.bias = bias

    def build_core(self, input_shape):
        from bigdl_tpu.nn.layers_extra import SpatialDilatedConvolution
        from bigdl_tpu.nn.shape_ops import Transpose, Unsqueeze, Squeeze

        steps, dim = input_shape
        conv = SpatialDilatedConvolution(
            dim, self.nb_filter, 1, self.filter_length,
            1, self.subsample_length, 0, 0, 1, self.atrous_rate,
            with_bias=self.bias)
        # (B, steps, dim) -> (B, dim, steps, 1) -> conv -> back
        core = (_containers.Sequential()
                .add(Transpose([(2, 3)]))           # (B, dim, steps)
                .add(Unsqueeze(4))                  # (B, dim, steps, 1)
                .add(conv)
                .add(Squeeze(4))                    # (B, F, steps')
                .add(Transpose([(2, 3)])))          # (B, steps', F)
        return _maybe_activation(core, self.activation)

    def compute_output_shape(self, input_shape):
        steps, dim = input_shape
        eff = (self.filter_length - 1) * self.atrous_rate + 1
        return ((steps - eff) // self.subsample_length + 1, self.nb_filter)
