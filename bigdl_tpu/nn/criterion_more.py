"""Third criterion batch (SURVEY.md §2.2 "~30 criterions" inventory).

Reference (UNVERIFIED, SURVEY.md §0): one class per file under
``.../bigdl/nn/`` — ``L1HingeEmbeddingCriterion``, ``PoissonCriterion``,
``TimeDistributedMaskCriterion``, plus the keras-heritage regression losses
(``MeanAbsolutePercentageCriterion``, ``MeanSquaredLogarithmicCriterion``,
``KullbackLeiblerDivergenceCriterion``, ``CategoricalCrossEntropy``).

All are pure scalar ``apply(input, target)`` functions (jit-fusable into the
train step); ``backward`` = ``jax.grad`` via the base class.
"""

from __future__ import annotations

from bigdl_tpu.nn.criterion import AbstractCriterion

_EPS = 1e-7


class L1HingeEmbeddingCriterion(AbstractCriterion):
    """Table input ``[x1, x2]`` with target ±1: L1 distance ``d`` between the
    pair; loss ``d`` for similar pairs (y=1), ``max(0, margin − d)`` for
    dissimilar (y=−1) (reference ``nn/L1HingeEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 1.0) -> None:
        super().__init__()
        self.margin = margin

    def apply(self, input, target):
        import jax.numpy as jnp

        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2))
        y = jnp.reshape(target, ())
        return jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))


class PoissonCriterion(AbstractCriterion):
    """Poisson regression NLL ``mean(pred − target·log(pred))`` (reference
    ``nn/PoissonCriterion.scala``)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        return jnp.mean(input - target * jnp.log(jnp.maximum(input, _EPS)))


class MeanAbsolutePercentageCriterion(AbstractCriterion):
    """``100 · mean(|t − p| / clamp(|t|, eps))`` (reference
    ``nn/MeanAbsolutePercentageCriterion.scala``)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        diff = jnp.abs(target - input) / jnp.maximum(jnp.abs(target), _EPS)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(AbstractCriterion):
    """``mean((log(t+1) − log(p+1))²)`` with inputs clamped to ≥ eps
    (reference ``nn/MeanSquaredLogarithmicCriterion.scala``)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        lp = jnp.log(jnp.maximum(input, _EPS) + 1.0)
        lt = jnp.log(jnp.maximum(target, _EPS) + 1.0)
        return jnp.mean((lt - lp) ** 2)


class KullbackLeiblerDivergenceCriterion(AbstractCriterion):
    """Keras-style KL divergence ``mean_rows Σ t·log(t/p)`` with both sides
    clipped to [eps, 1] (reference
    ``nn/KullbackLeiblerDivergenceCriterion.scala``). Distinct from
    ``DistKLDivCriterion`` (log-prob input) and ``KLDCriterion`` (VAE prior)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        p = jnp.clip(input, _EPS, 1.0)
        t = jnp.clip(target, _EPS, 1.0)
        per_row = jnp.sum(t * jnp.log(t / p), axis=-1)
        return jnp.mean(per_row)


class CategoricalCrossEntropy(AbstractCriterion):
    """Cross entropy over PROBABILITY input with one-hot targets (reference
    ``nn/CategoricalCrossEntropy.scala``, keras heritage) — unlike
    ``ClassNLLCriterion`` (log-prob + class-index target)."""

    def apply(self, input, target):
        import jax.numpy as jnp

        p = jnp.clip(input, _EPS, 1.0 - _EPS)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        return jnp.mean(-jnp.sum(target * jnp.log(p), axis=-1))


class TimeDistributedMaskCriterion(AbstractCriterion):
    """Per-timestep criterion that MASKS padded steps — steps whose target
    equals ``padding_value`` contribute nothing, and the mean divides by the
    number of real steps (reference ``nn/TimeDistributedMaskCriterion.scala``).

    TPU-native: instead of slicing per step, the wrapped criterion is vmapped
    over (batch·time) and multiplied by the mask — static shapes, one fused
    reduction."""

    def __init__(self, critrn: AbstractCriterion,
                 padding_value: int = 0) -> None:
        super().__init__()
        self.critrn = critrn
        self.padding_value = padding_value

    def apply(self, input, target):
        import jax
        import jax.numpy as jnp

        b, t = input.shape[0], input.shape[1]
        flat_in = input.reshape((b * t,) + input.shape[2:])
        flat_tg = target.reshape((b * t,) + target.shape[2:])
        per = jax.vmap(lambda i, g: self.critrn.apply(i[None], g[None]))(
            flat_in, flat_tg)
        mask_nd = (flat_tg != self.padding_value)
        mask = mask_nd if mask_nd.ndim == 1 else mask_nd.reshape(b * t, -1).any(axis=-1)
        mask = mask.astype(per.dtype)
        return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class MaskedSoftmaxCECriterion(AbstractCriterion):
    """Sequence cross-entropy straight from LOGITS ``(B, T, V)`` against
    1-based targets ``(B, T)``, masking ``padding_value`` steps — the
    fused form of ``TimeDistributedMaskCriterion(CrossEntropyCriterion)``
    over a ``TransformerLM(output="logits")``.

    Why it exists (TPU): the unfused pipeline materializes the full
    ``(B, T, V)`` log-prob tensor (LogSoftMax writes it, NLL re-reads it)
    — at LM scale that is gigabytes of pure HBM traffic per step. Here
    the loss is ``logsumexp(logits) - logits[target]`` (one reduction +
    one gather, no log-prob tensor), and the backward's
    ``softmax - onehot`` is generated inside one fusion. Identical math.
    """

    def __init__(self, padding_value: int = 0) -> None:
        super().__init__()
        self.padding_value = int(padding_value)

    def apply(self, input, target):
        import jax
        import jax.numpy as jnp

        b, t, v = input.shape
        logits = input.reshape(b * t, v)
        tg = target.reshape(b * t).astype(jnp.int32)
        idx = jnp.clip(tg - 1, 0, v - 1)          # 1-based reference ids
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logits, idx[:, None], axis=-1)[:, 0].astype(jnp.float32)
        per = lse - picked
        mask = (tg != self.padding_value).astype(per.dtype)
        return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
