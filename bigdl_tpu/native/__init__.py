"""Native host-side runtime: C++ image ops + prefetch executor via ctypes.

Reference (UNVERIFIED, SURVEY.md §0/§2.1): the native row-set — MKL JNI
(``com.intel.analytics.bigdl.mkl.MKL``), MKL-DNN JNI, and OpenCV JNI
(``.../transform/vision/image/opencv/OpenCVMat.scala``) — plus the
``Engine.default`` ThreadPool that drives the data path. On TPU the math
backend is XLA/Pallas; what remains genuinely native is the host data
plane, rebuilt here in C++ (``src/bigdl_native.cpp``):

* ``augment_batch`` — crop/flip/normalize, HWC u8 → CHW f32 (OpenCV role)
* ``resize_bilinear`` — batched bilinear resize
* ``decode_cifar`` — binary record split
* ``NativeLoader`` — threaded bounded prefetch executor (ThreadPool role)

Availability is probed lazily; ``is_available()`` is False when no C++
toolchain exists, and callers (``bigdl_tpu.dataset``) fall back to numpy.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

_lib = None
_lib_error: Optional[str] = None


def _load():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        from bigdl_tpu.native.build import build_library
        path = build_library()
        lib = ctypes.CDLL(path)
    except OSError as e:
        _lib_error = str(e)
        return None
    c_u8p = ctypes.POINTER(ctypes.c_uint8)
    c_i32p = ctypes.POINTER(ctypes.c_int32)
    c_f32p = ctypes.POINTER(ctypes.c_float)
    i32 = ctypes.c_int32
    lib.bigdl_augment_batch.argtypes = [
        c_u8p, i32, i32, i32, i32, c_i32p, c_i32p, c_u8p, i32, i32,
        c_f32p, c_f32p, c_f32p, i32]
    lib.bigdl_resize_bilinear.argtypes = [
        c_u8p, i32, i32, i32, i32, c_u8p, i32, i32, i32]
    lib.bigdl_decode_cifar.argtypes = [
        c_u8p, i32, i32, i32, c_u8p, c_i32p, i32, i32]
    lib.bigdl_loader_create.restype = ctypes.c_void_p
    lib.bigdl_loader_create.argtypes = [
        i32, i32, i32, i32, i32, i32, c_f32p, c_f32p, i32, i32]
    lib.bigdl_loader_push.restype = i32
    lib.bigdl_loader_push.argtypes = [
        ctypes.c_void_p, c_u8p, c_i32p, c_i32p, c_i32p, c_u8p]
    lib.bigdl_loader_pop.restype = i32
    lib.bigdl_loader_pop.argtypes = [ctypes.c_void_p, c_f32p, c_i32p]
    lib.bigdl_loader_stop.argtypes = [ctypes.c_void_p]
    lib.bigdl_loader_destroy.argtypes = [ctypes.c_void_p]
    i64 = ctypes.c_int64
    c_i64p = ctypes.POINTER(i64)
    lib.bigdl_recs_index.restype = i64
    lib.bigdl_recs_index.argtypes = [c_u8p, i64, i64, c_i64p, c_i64p, c_i64p]
    _lib = lib
    return _lib


def is_available() -> bool:
    return _load() is not None


def unavailable_reason() -> Optional[str]:
    _load()
    return _lib_error


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def augment_batch(images: np.ndarray, off_y: np.ndarray, off_x: np.ndarray,
                  flip: np.ndarray, crop_h: int, crop_w: int,
                  mean, std, n_threads: int = 4) -> np.ndarray:
    """(n, H, W, C) u8 → (n, C, crop_h, crop_w) f32, crop/flip/normalize."""
    lib = _load()
    assert lib is not None, _lib_error
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    off_y = np.ascontiguousarray(off_y, np.int32)
    off_x = np.ascontiguousarray(off_x, np.int32)
    flip = np.ascontiguousarray(flip, np.uint8)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    out = np.empty((n, c, crop_h, crop_w), np.float32)
    lib.bigdl_augment_batch(_u8(images), n, h, w, c, _i32(off_y), _i32(off_x),
                            _u8(flip), crop_h, crop_w, _f32(mean), _f32(std),
                            _f32(out), n_threads)
    return out


def resize_bilinear(images: np.ndarray, dst_h: int, dst_w: int,
                    n_threads: int = 4) -> np.ndarray:
    """(n, H, W, C) u8 → (n, dst_h, dst_w, C) u8, half-pixel bilinear."""
    lib = _load()
    assert lib is not None, _lib_error
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    out = np.empty((n, dst_h, dst_w, c), np.uint8)
    lib.bigdl_resize_bilinear(_u8(images), n, h, w, c, _u8(out), dst_h, dst_w,
                              n_threads)
    return out


def decode_cifar(records: np.ndarray, record_len: int = 3073,
                 label_offset: int = 0, label_base: int = 1,
                 n_threads: int = 4):
    """Raw .bin bytes → ((n, 3, 32, 32) u8 planar, (n,) int32 labels).

    label_base=1 matches the reference's 1-based ClassNLL labels.
    """
    lib = _load()
    assert lib is not None, _lib_error
    records = np.ascontiguousarray(records, np.uint8).reshape(-1)
    n = records.size // record_len
    img_len = record_len - label_offset - 1
    images = np.empty((n, img_len), np.uint8)
    labels = np.empty((n,), np.int32)
    lib.bigdl_decode_cifar(_u8(records), n, record_len, label_offset,
                           _u8(images), _i32(labels), label_base, n_threads)
    return images.reshape(n, 3, 32, 32), labels


class NativeLoader:
    """Bounded prefetch executor over the C++ worker pool.

    push() copies a batch of raw HWC u8 images + host-drawn aug params into
    the library (blocking when queue_depth batches are in flight); pop()
    returns the oldest finished (images_f32_CHW, labels_i32) batch. The
    augmentation pipeline runs off-GIL in C++ threads, overlapping with the
    TPU step — the DistriOptimizer data-feed analog of Engine.default.
    """

    def __init__(self, batch: int, src_h: int, src_w: int, c: int,
                 crop_h: int, crop_w: int, mean, std,
                 queue_depth: int = 4, n_workers: int = 4) -> None:
        lib = _load()
        assert lib is not None, _lib_error
        self._lib = lib
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        assert mean.size == c and std.size == c
        self._h = lib.bigdl_loader_create(batch, src_h, src_w, c, crop_h,
                                          crop_w, _f32(mean), _f32(std),
                                          queue_depth, n_workers)
        self.batch, self.c, self.crop_h, self.crop_w = batch, c, crop_h, crop_w

    def push(self, images: np.ndarray, labels: np.ndarray,
             off_y: np.ndarray, off_x: np.ndarray, flip: np.ndarray) -> None:
        images = np.ascontiguousarray(images, np.uint8)
        labels = np.ascontiguousarray(labels, np.int32)
        off_y = np.ascontiguousarray(off_y, np.int32)
        off_x = np.ascontiguousarray(off_x, np.int32)
        flip = np.ascontiguousarray(flip, np.uint8)
        rc = self._lib.bigdl_loader_push(self._h, _u8(images), _i32(labels),
                                         _i32(off_y), _i32(off_x), _u8(flip))
        if rc != 0:
            raise RuntimeError("NativeLoader stopped")

    def pop(self):
        out = np.empty((self.batch, self.c, self.crop_h, self.crop_w),
                       np.float32)
        labels = np.empty((self.batch,), np.int32)
        rc = self._lib.bigdl_loader_pop(self._h, _f32(out), _i32(labels))
        if rc != 0:
            raise RuntimeError("NativeLoader stopped and drained")
        return out, labels

    def stop(self) -> None:
        """Unblocks every thread waiting in push/pop (they raise
        RuntimeError). Must precede close() when producer threads exist —
        close() frees the loader, so no thread may still be inside a call."""
        if self._h:
            self._lib.bigdl_loader_stop(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.bigdl_loader_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def recs_index(buf: np.ndarray):
    """Index a RECS shard buffer (uint8, starting at the magic).

    Returns ``(labels int64[n], offsets int64[n], lengths int64[n])``.
    Raises ValueError on malformed data. Grows capacity and retries when the
    first guess undershoots (the C side returns -2 in that case).
    """
    import ctypes

    lib = _load()
    if lib is None:
        raise OSError(unavailable_reason() or "native library unavailable")
    buf = np.ascontiguousarray(buf, np.uint8)
    cap = max(1024, buf.size // 64)  # ≥16 B/record heuristic first guess
    while True:
        labels = np.empty(cap, np.int64)
        offsets = np.empty(cap, np.int64)
        lengths = np.empty(cap, np.int64)
        n = lib.bigdl_recs_index(
            _u8(buf), ctypes.c_int64(buf.size), ctypes.c_int64(cap),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if n == -1:
            raise ValueError("malformed RECS shard")
        if n == -2:
            cap *= 4
            continue
        return labels[:n].copy(), offsets[:n].copy(), lengths[:n].copy()
