"""On-demand build of the native runtime (g++ → shared library).

The reference ships its native backends as prebuilt JNI jars (bigdl-core);
here the library is compiled once per source change with the system g++ and
cached next to the sources. No external deps — pure C++17 + pthreads.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile
import threading

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LOCK = threading.Lock()


def _source_digest() -> str:
    h = hashlib.sha256()
    for name in sorted(os.listdir(_SRC_DIR)):
        with open(os.path.join(_SRC_DIR, name), "rb") as f:
            h.update(name.encode())
            h.update(f.read())
    return h.hexdigest()[:16]


def build_library() -> str:
    """Compiles (if needed) and returns the path to libbigdl_native.so.

    Raises OSError when no working C++ toolchain is available; callers fall
    back to the numpy path.
    """
    with _LOCK:
        digest = _source_digest()
        out = os.path.join(_OUT_DIR, f"libbigdl_native-{digest}.so")
        if os.path.exists(out):
            return out
        os.makedirs(_OUT_DIR, exist_ok=True)
        # unique tmp per builder: concurrent processes may race to build the
        # same digest; each compiles privately, last os.replace wins (same
        # bits either way)
        fd, tmp = tempfile.mkstemp(dir=_OUT_DIR, suffix=".so.tmp")
        os.close(fd)
        cmd = [
            "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
            os.path.join(_SRC_DIR, "bigdl_native.cpp"), "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, out)
        except FileNotFoundError as e:
            raise OSError("g++ not found; native runtime unavailable") from e
        except subprocess.CalledProcessError as e:
            raise OSError(f"native build failed:\n{e.stderr}") from e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return out
