// bigdl_tpu native runtime — C API.
//
// Role (SURVEY.md §2.1, native row-set): the reference ships C/C++ JNI
// backends (MKL, MKL-DNN, OpenCV) under its JVM tensor/data layers. On TPU
// the *math* backend is XLA/Pallas, but the host-side data plane — image
// augmentation, record decode, and the prefetch executor that keeps the chip
// fed — is the part that still wants native code (the OpenCV-JNI +
// Engine.ThreadPool analog). This library is loaded from Python via ctypes.
//
// Threading model: a fixed worker pool (std::thread) inside the library;
// Python enqueues jobs whose randomness (crop offsets, flip flags) was
// already drawn host-side, so C++ is purely deterministic data movement.
#pragma once
#include <cstdint>

extern "C" {

// ---- stateless batch ops (parallelised internally over n_threads) ----

// HWC uint8 -> CHW float32 with per-image crop/flip and per-channel
// (x - mean) / std. src: n*(src_h*src_w*c); dst: n*(c*crop_h*crop_w).
void bigdl_augment_batch(const uint8_t* src, int32_t n, int32_t src_h,
                         int32_t src_w, int32_t c, const int32_t* off_y,
                         const int32_t* off_x, const uint8_t* flip,
                         int32_t crop_h, int32_t crop_w, const float* mean,
                         const float* stdv, float* dst, int32_t n_threads);

// Bilinear resize, HWC uint8 -> HWC uint8 (half-pixel centres, like
// OpenCV INTER_LINEAR / jax.image.resize "linear").
void bigdl_resize_bilinear(const uint8_t* src, int32_t n, int32_t src_h,
                           int32_t src_w, int32_t c, uint8_t* dst,
                           int32_t dst_h, int32_t dst_w, int32_t n_threads);

// CIFAR-10/100 .bin records: [label u8][3072 u8 planar RGB] each.
// Splits into labels (int32, +label_base) and planar CHW uint8 images.
void bigdl_decode_cifar(const uint8_t* records, int32_t n,
                        int32_t record_len, int32_t label_offset,
                        uint8_t* images, int32_t* labels, int32_t label_base,
                        int32_t n_threads);

// ---- record-shard indexing ----

// Index a RECS shard held in memory: buf starts at the 4-byte "RECS" magic;
// records follow as [varint label][varint payload_len][payload]. Fills
// labels[i], offsets[i] (payload byte offset from buf start) and lengths[i]
// for up to n_max records. Returns the record count, -1 on malformed data
// (bad magic / truncated record / varint overflow), or -2 when the shard
// holds more than n_max records (call again with a larger capacity).
// One sequential scan — varint chains can't be split — but ~two orders of
// magnitude faster than a Python byte loop on multi-GB shards.
int64_t bigdl_recs_index(const uint8_t* buf, int64_t size, int64_t n_max,
                         int64_t* labels, int64_t* offsets, int64_t* lengths);

// ---- prefetch executor ----
// A bounded ring of batch slots filled by the worker pool; Python pushes
// raw-record jobs (data is copied in) and pops completed float32 batches.
// This is the native analog of the reference's Engine.default ThreadPool
// feeding MiniBatches to the optimizer.

typedef struct bigdl_loader bigdl_loader;

// Creates a loader producing (batch, c, crop_h, crop_w) float32 batches
// from (src_h, src_w, c) uint8 HWC images. queue_depth = max in-flight
// batches; n_workers = worker threads.
bigdl_loader* bigdl_loader_create(int32_t batch, int32_t src_h, int32_t src_w,
                                  int32_t c, int32_t crop_h, int32_t crop_w,
                                  const float* mean, const float* stdv,
                                  int32_t queue_depth, int32_t n_workers);

// Enqueue one batch job. Copies `batch` images (+ labels + aug params) into
// an internal arena, then returns; blocks only when queue_depth jobs are
// already in flight. Returns 0 on success, -1 if the loader was stopped.
int32_t bigdl_loader_push(bigdl_loader* L, const uint8_t* images,
                          const int32_t* labels, const int32_t* off_y,
                          const int32_t* off_x, const uint8_t* flip);

// Dequeue the oldest completed batch into caller buffers (FIFO order).
// Blocks until one is ready. Returns 0, or -1 if stopped and drained.
int32_t bigdl_loader_pop(bigdl_loader* L, float* out_images,
                         int32_t* out_labels);

// Marks the loader stopped and wakes every blocked push/pop. Safe to call
// while other threads are inside push/pop; they return -1. Call this (and
// join producer threads) BEFORE destroy, which frees the loader.
void bigdl_loader_stop(bigdl_loader* L);

void bigdl_loader_destroy(bigdl_loader* L);

}  // extern "C"
