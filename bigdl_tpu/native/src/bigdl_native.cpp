// bigdl_tpu native runtime — implementation. See bigdl_native.h.
#include "bigdl_native.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <functional>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) across up to n_threads transient threads.
// Image batches are short jobs; thread start-up cost is amortised over
// whole batches, and the persistent pool lives in bigdl_loader instead.
void parallel_for(int32_t n, int32_t n_threads,
                  const std::function<void(int32_t)>& fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int32_t i = 0; i < n; ++i) fn(i);
    return;
  }
  int32_t workers = std::min(n, n_threads);
  std::atomic<int32_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (int32_t w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      for (int32_t i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
    });
  }
  for (auto& t : pool) t.join();
}

// One image: HWC uint8 crop/flip -> CHW float32 normalize.
void augment_one(const uint8_t* img, int32_t src_h, int32_t src_w, int32_t c,
                 int32_t oy, int32_t ox, bool flip, int32_t crop_h,
                 int32_t crop_w, const float* mean, const float* stdv,
                 float* dst) {
  for (int32_t ch = 0; ch < c; ++ch) {
    const float m = mean[ch];
    const float inv = 1.0f / stdv[ch];
    float* out = dst + (size_t)ch * crop_h * crop_w;
    for (int32_t y = 0; y < crop_h; ++y) {
      const uint8_t* row = img + ((size_t)(oy + y) * src_w + ox) * c + ch;
      float* orow = out + (size_t)y * crop_w;
      if (!flip) {
        for (int32_t x = 0; x < crop_w; ++x)
          orow[x] = ((float)row[(size_t)x * c] - m) * inv;
      } else {
        for (int32_t x = 0; x < crop_w; ++x)
          orow[crop_w - 1 - x] = ((float)row[(size_t)x * c] - m) * inv;
      }
    }
  }
}

void resize_one(const uint8_t* src, int32_t sh, int32_t sw, int32_t c,
                uint8_t* dst, int32_t dh, int32_t dw) {
  const float sy = (float)sh / dh, sx = (float)sw / dw;
  for (int32_t y = 0; y < dh; ++y) {
    float fy = ((float)y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int32_t y0 = (int32_t)fy;
    int32_t y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    for (int32_t x = 0; x < dw; ++x) {
      float fx = ((float)x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int32_t x0 = (int32_t)fx;
      int32_t x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      for (int32_t ch = 0; ch < c; ++ch) {
        float v00 = src[((size_t)y0 * sw + x0) * c + ch];
        float v01 = src[((size_t)y0 * sw + x1) * c + ch];
        float v10 = src[((size_t)y1 * sw + x0) * c + ch];
        float v11 = src[((size_t)y1 * sw + x1) * c + ch];
        float top = v00 + (v01 - v00) * wx;
        float bot = v10 + (v11 - v10) * wx;
        float v = top + (bot - top) * wy;
        dst[((size_t)y * dw + x) * c + ch] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

void bigdl_augment_batch(const uint8_t* src, int32_t n, int32_t src_h,
                         int32_t src_w, int32_t c, const int32_t* off_y,
                         const int32_t* off_x, const uint8_t* flip,
                         int32_t crop_h, int32_t crop_w, const float* mean,
                         const float* stdv, float* dst, int32_t n_threads) {
  const size_t in_stride = (size_t)src_h * src_w * c;
  const size_t out_stride = (size_t)c * crop_h * crop_w;
  parallel_for(n, n_threads, [&](int32_t i) {
    augment_one(src + i * in_stride, src_h, src_w, c, off_y[i], off_x[i],
                flip[i] != 0, crop_h, crop_w, mean, stdv, dst + i * out_stride);
  });
}

void bigdl_resize_bilinear(const uint8_t* src, int32_t n, int32_t src_h,
                           int32_t src_w, int32_t c, uint8_t* dst,
                           int32_t dst_h, int32_t dst_w, int32_t n_threads) {
  const size_t in_stride = (size_t)src_h * src_w * c;
  const size_t out_stride = (size_t)dst_h * dst_w * c;
  parallel_for(n, n_threads, [&](int32_t i) {
    resize_one(src + i * in_stride, src_h, src_w, c, dst + i * out_stride,
               dst_h, dst_w);
  });
}

void bigdl_decode_cifar(const uint8_t* records, int32_t n, int32_t record_len,
                        int32_t label_offset, uint8_t* images, int32_t* labels,
                        int32_t label_base, int32_t n_threads) {
  const int32_t img_len = record_len - label_offset - 1;
  parallel_for(n, n_threads, [&](int32_t i) {
    const uint8_t* rec = records + (size_t)i * record_len;
    labels[i] = (int32_t)rec[label_offset] + label_base;
    std::memcpy(images + (size_t)i * img_len, rec + label_offset + 1, img_len);
  });
}

}  // extern "C"

// ---------------- prefetch executor ----------------

struct Job {
  std::vector<uint8_t> images;
  std::vector<int32_t> labels;
  std::vector<int32_t> off_y, off_x;
  std::vector<uint8_t> flip;
  std::vector<float> out;  // filled by a worker
  bool done = false;
};

struct bigdl_loader {
  int32_t batch, src_h, src_w, c, crop_h, crop_w, queue_depth;
  std::vector<float> mean, stdv;

  std::mutex mu;
  std::condition_variable cv_push, cv_pop, cv_work;
  // FIFO of jobs; workers claim the first unclaimed one. Completed jobs are
  // popped strictly in push order so batch<->epoch bookkeeping stays simple.
  std::deque<Job*> jobs;      // owned; front = oldest
  std::deque<Job*> pending;   // subset of jobs not yet claimed by a worker
  bool stopped = false;
  std::vector<std::thread> workers;

  void worker_loop() {
    for (;;) {
      Job* j;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stopped || !pending.empty(); });
        if (stopped && pending.empty()) return;
        j = pending.front();
        pending.pop_front();
      }
      j->out.resize((size_t)batch * c * crop_h * crop_w);
      const size_t in_stride = (size_t)src_h * src_w * c;
      const size_t out_stride = (size_t)c * crop_h * crop_w;
      for (int32_t i = 0; i < batch; ++i)
        augment_one(j->images.data() + i * in_stride, src_h, src_w, c,
                    j->off_y[i], j->off_x[i], j->flip[i] != 0, crop_h, crop_w,
                    mean.data(), stdv.data(), j->out.data() + i * out_stride);
      {
        std::lock_guard<std::mutex> lk(mu);
        j->done = true;
        cv_pop.notify_all();
      }
    }
  }
};

extern "C" {

bigdl_loader* bigdl_loader_create(int32_t batch, int32_t src_h, int32_t src_w,
                                  int32_t c, int32_t crop_h, int32_t crop_w,
                                  const float* mean, const float* stdv,
                                  int32_t queue_depth, int32_t n_workers) {
  auto* L = new bigdl_loader;
  L->batch = batch;
  L->src_h = src_h;
  L->src_w = src_w;
  L->c = c;
  L->crop_h = crop_h;
  L->crop_w = crop_w;
  L->queue_depth = queue_depth > 0 ? queue_depth : 2;
  L->mean.assign(mean, mean + c);
  L->stdv.assign(stdv, stdv + c);
  if (n_workers < 1) n_workers = 1;
  for (int32_t i = 0; i < n_workers; ++i)
    L->workers.emplace_back([L] { L->worker_loop(); });
  return L;
}

int32_t bigdl_loader_push(bigdl_loader* L, const uint8_t* images,
                          const int32_t* labels, const int32_t* off_y,
                          const int32_t* off_x, const uint8_t* flip) {
  auto* j = new Job;
  const size_t img_bytes = (size_t)L->batch * L->src_h * L->src_w * L->c;
  j->images.assign(images, images + img_bytes);
  j->labels.assign(labels, labels + L->batch);
  j->off_y.assign(off_y, off_y + L->batch);
  j->off_x.assign(off_x, off_x + L->batch);
  j->flip.assign(flip, flip + L->batch);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_push.wait(lk, [&] {
    return L->stopped || (int32_t)L->jobs.size() < L->queue_depth;
  });
  if (L->stopped) {
    delete j;
    return -1;
  }
  L->jobs.push_back(j);
  L->pending.push_back(j);
  L->cv_work.notify_one();
  return 0;
}

int32_t bigdl_loader_pop(bigdl_loader* L, float* out_images,
                         int32_t* out_labels) {
  Job* j;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_pop.wait(lk, [&] {
      return (!L->jobs.empty() && L->jobs.front()->done) ||
             (L->stopped && L->jobs.empty());
    });
    if (L->jobs.empty()) return -1;
    j = L->jobs.front();
    L->jobs.pop_front();
    L->cv_push.notify_one();
  }
  std::memcpy(out_images, j->out.data(), j->out.size() * sizeof(float));
  std::memcpy(out_labels, j->labels.data(), L->batch * sizeof(int32_t));
  delete j;
  return 0;
}

void bigdl_loader_stop(bigdl_loader* L) {
  std::lock_guard<std::mutex> lk(L->mu);
  L->stopped = true;
  L->cv_work.notify_all();
  L->cv_push.notify_all();
  L->cv_pop.notify_all();
}

void bigdl_loader_destroy(bigdl_loader* L) {
  bigdl_loader_stop(L);
  for (auto& t : L->workers) t.join();
  for (auto* j : L->jobs) delete j;
  delete L;
}


int64_t bigdl_recs_index(const uint8_t* buf, int64_t size, int64_t n_max,
                         int64_t* labels, int64_t* offsets, int64_t* lengths) {
  if (size < 4 || std::memcmp(buf, "RECS", 4) != 0) return -1;
  int64_t pos = 4;
  int64_t n = 0;
  auto read_varint = [&](uint64_t* out) -> bool {
    uint64_t result = 0;
    int shift = 0;
    while (pos < size) {
      uint8_t b = buf[pos++];
      result |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        *out = result;
        return true;
      }
      shift += 7;
      if (shift > 63) return false;  // varint overflow
    }
    return false;  // truncated
  };
  while (pos < size) {
    uint64_t label, len;
    if (!read_varint(&label)) return -1;
    if (!read_varint(&len)) return -1;
    if (pos + (int64_t)len > size) return -1;  // truncated payload
    if (n >= n_max) return -2;
    // full varint width: the pure-Python reader yields the whole value,
    // so a >=2^31 label must decode identically on both paths
    labels[n] = (int64_t)label;
    offsets[n] = pos;
    lengths[n] = (int64_t)len;
    pos += (int64_t)len;
    ++n;
  }
  return n;
}

}  // extern "C"
