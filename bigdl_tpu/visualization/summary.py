"""TrainSummary / ValidationSummary (reference ``visualization/Summary.scala``,
``TrainSummary.scala``, ``ValidationSummary.scala``).

``Optimizer.set_train_summary``/``set_val_summary`` hook these into the
training loop; TrainSummary records Loss/Throughput (+ LearningRate when the
optim method exposes one), ValidationSummary records each ValidationMethod's
score. ``read_scalar(tag)`` reads a tag's history back (reference
``readScalar``) — used by tests and notebook-style inspection.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from bigdl_tpu.visualization.tensorboard import FileWriter, read_scalars


class Summary:
    def __init__(self, log_dir: str, app_name: str, tag: str) -> None:
        self.log_dir = os.path.join(log_dir, app_name, tag)
        self.writer = FileWriter(self.log_dir)
        self._triggers = {}

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self.writer.add_scalar(tag, float(value), int(step))
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self.writer.add_histogram(tag, values, int(step))
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """(step, value) history of one tag across this summary's files."""
        out = []
        for name in sorted(os.listdir(self.log_dir)):
            for t, v, step in read_scalars(os.path.join(self.log_dir, name)):
                if t == tag:
                    out.append((step, v))
        return out

    def close(self) -> None:
        self.writer.close()


class TrainSummary(Summary):
    def __init__(self, log_dir: str, app_name: str) -> None:
        super().__init__(log_dir, app_name, "train")

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        """Per-tag recording triggers (reference: throttles the expensive
        'Parameters' histograms, e.g. ``Trigger.several_iteration(20)``)."""
        self._triggers[name] = trigger
        return self

    def should_record(self, name: str, state) -> bool:
        trig = self._triggers.get(name)
        return trig is not None and trig(state)


class ValidationSummary(Summary):
    def __init__(self, log_dir: str, app_name: str) -> None:
        super().__init__(log_dir, app_name, "validation")
