"""Visualization — TensorBoard summaries (reference layer L10, SURVEY.md §2.9/§5.5).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/visualization/`` —
``TrainSummary`` (Loss / Throughput / LearningRate scalars, optional
parameter histograms), ``ValidationSummary`` (per-validation accuracy), both
written by an in-repo TF-event-file writer with CRC-masked record framing
(``visualization/tensorboard/{FileWriter, EventWriter}``) so there is no
TensorFlow dependency. The rebuild keeps that property: the protobuf
``Event``/``Summary`` encoding and the TFRecord CRC32C framing are
hand-rolled below (~60 lines), and files are readable by any TensorBoard.
"""

from bigdl_tpu.visualization.tensorboard import FileWriter, read_scalars
from bigdl_tpu.visualization.summary import (
    Summary, TrainSummary, ValidationSummary,
)

__all__ = [
    "FileWriter", "read_scalars", "Summary", "TrainSummary",
    "ValidationSummary",
]
