"""Minimal TF-event-file writer/reader (no TensorFlow dependency).

Reference (UNVERIFIED, SURVEY.md §0):
``.../bigdl/visualization/tensorboard/{FileWriter, EventWriter, Summary}`` —
BigDL ships its own event writer emitting protobuf ``Event`` records with
CRC-masked TFRecord framing for exactly the same reason (no TF dep on the
Spark cluster). Encodings implemented by hand:

* protobuf wire format for the two messages used
  (``Event``: wall_time=1 double, step=2 int64, file_version=3 string,
  summary=5 message; ``Summary.Value``: tag=1 string, simple_value=2 float)
* TFRecord framing: u64-le length, masked-crc32c(length), payload,
  masked-crc32c(payload).
"""

from __future__ import annotations

import os
import struct
import time
from typing import Dict, List, Tuple

# -- crc32c (Castagnoli, reflected poly 0x82F63B78) ------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire helpers (single shared definition in utils/protowire) ---

from bigdl_tpu.utils.protowire import (  # noqa: E402
    field_bytes as _field_bytes,
    field_double as _field_double,
    field_float as _field_float,
    field_varint as _field_varint,
)


def scalar_event(tag: str, value: float, step: int,
                 wall_time: float | None = None) -> bytes:
    sv = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    summary = _field_bytes(1, sv)
    return (_field_double(1, wall_time if wall_time is not None else time.time())
            + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def _packed_doubles(num: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return _field_bytes(num, payload)


def histogram_event(tag: str, values, step: int,
                    bins: int = 30, wall_time: float | None = None) -> bytes:
    """TF HistogramProto event (reference ``Summary.histogram`` — the
    'Parameters' histograms of TrainSummary)."""
    import numpy as np

    v = np.asarray(values, np.float64).reshape(-1)
    if v.size == 0:
        v = np.zeros((1,))
    counts, edges = np.histogram(v, bins=bins)
    histo = (_field_double(1, float(v.min()))
             + _field_double(2, float(v.max()))
             + _field_double(3, float(v.size))
             + _field_double(4, float(v.sum()))
             + _field_double(5, float((v * v).sum()))
             + _packed_doubles(6, edges[1:])
             + _packed_doubles(7, counts))
    sv = _field_bytes(1, tag.encode()) + _field_bytes(5, histo)
    summary = _field_bytes(1, sv)
    return (_field_double(1, wall_time if wall_time is not None else time.time())
            + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def version_event() -> bytes:
    return (_field_double(1, time.time())
            + _field_bytes(3, b"brain.Event:2"))


def frame_record(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header))
            + payload + struct.pack("<I", _masked_crc(payload)))


class FileWriter:
    """Append-only event-file writer (reference ``tensorboard/FileWriter``).
    File name follows the TB convention ``events.out.tfevents.<ts>.<tag>``."""

    def __init__(self, log_dir: str, suffix: str = "bigdl_tpu") -> None:
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(
            log_dir, f"events.out.tfevents.{int(time.time()*1e6)}.{suffix}"
        )
        self._f = open(self.path, "ab")
        self._f.write(frame_record(version_event()))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._f.write(frame_record(scalar_event(tag, value, step)))
        self._f.flush()

    def add_histogram(self, tag: str, values, step: int) -> None:
        self._f.write(frame_record(histogram_event(tag, values, step)))
        self._f.flush()

    def close(self) -> None:
        self._f.close()


# -- reader (for tests and BigDL-style readScalar) -------------------------

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _parse_event(buf: bytes) -> Dict:
    i, out = 0, {}
    while i < len(buf):
        key, i = _read_varint(buf, i)
        num, wire = key >> 3, key & 7
        if wire == 1:
            val = struct.unpack_from("<d", buf, i)[0]; i += 8
        elif wire == 5:
            val = struct.unpack_from("<f", buf, i)[0]; i += 4
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]; i += ln
        else:
            val, i = _read_varint(buf, i)
        out.setdefault(num, []).append(val)
    return out


def read_scalars(path: str) -> List[Tuple[str, float, int]]:
    """Parse an event file back into (tag, value, step) triples."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    i = 0
    while i + 12 <= len(data):
        (ln,) = struct.unpack_from("<Q", data, i)
        payload = data[i + 12:i + 12 + ln]
        i += 12 + ln + 4
        ev = _parse_event(payload)
        step = ev.get(2, [0])[0]
        for summary in ev.get(5, []):
            for value_msg in _parse_event(summary).get(1, []):
                v = _parse_event(value_msg)
                if 1 in v and 2 in v:
                    out.append((v[1][0].decode(), v[2][0], step))
    return out
