"""Inception-v1 (GoogLeNet) — BASELINE config #4 (ImageNet, poly LR).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/inception/
Inception.scala`` — ``Inception_v1(classNum)`` / ``Inception_v1_NoAuxClassifier``;
inception blocks are a 4-way ``Concat(2)`` (1x1 | 1x1→3x3 | 1x1→5x5 |
maxpool→1x1), stem is 7x7/2 conv → maxpool(ceil) → LRN → 1x1 → 3x3 → LRN →
maxpool, head is 7x7 avgpool → Dropout(0.4) → Linear(1024, classNum) →
LogSoftMax. Xavier init throughout.

TPU-native notes: the four branches are independent convs over the same
input — XLA schedules them back-to-back on the MXU and the ``Concat`` is a
layout no-op folded into the next conv's operand. Ceil-mode pooling maps to
explicit -inf padding in ``lax.reduce_window``.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Concat, Dropout, Linear, LogSoftMax, ReLU, Reshape, Sequential,
    SpatialAveragePooling, SpatialConvolution, SpatialCrossMapLRN,
    SpatialMaxPooling, Xavier, Zeros,
)


def _conv_relu(seq: Sequential, n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0,
               name: str = "") -> Sequential:
    """Append Xavier-init conv + in-place ReLU to ``seq`` (every conv in this
    net uses exactly this pattern)."""
    seq.add(
        SpatialConvolution(
            n_in, n_out, kw, kh, sw, sh, pw, ph,
            init_weight=Xavier(), init_bias=Zeros(),
        ).set_name(name)
    )
    seq.add(ReLU(True))
    return seq


def Inception_Layer_v1(input_size: int, config, name_prefix: str = "") -> Concat:
    """One inception block. ``config`` is reference-style:
    ``T(T(out1x1), T(reduce3x3, out3x3), T(reduce5x5, out5x5), T(pool_proj))``
    — accepted here as a nested list/tuple."""
    c = [list(branch) for branch in config]
    concat = Concat(2)

    b1 = _conv_relu(Sequential(), input_size, c[0][0], 1, 1,
                    name=name_prefix + "1x1")
    concat.add(b1)

    b2 = _conv_relu(Sequential(), input_size, c[1][0], 1, 1,
                    name=name_prefix + "3x3_reduce")
    _conv_relu(b2, c[1][0], c[1][1], 3, 3, 1, 1, 1, 1,
               name=name_prefix + "3x3")
    concat.add(b2)

    b3 = _conv_relu(Sequential(), input_size, c[2][0], 1, 1,
                    name=name_prefix + "5x5_reduce")
    _conv_relu(b3, c[2][0], c[2][1], 5, 5, 1, 1, 2, 2,
               name=name_prefix + "5x5")
    concat.add(b3)

    b4 = Sequential()
    b4.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil().set_name(name_prefix + "pool"))
    _conv_relu(b4, input_size, c[3][0], 1, 1, name=name_prefix + "pool_proj")
    concat.add(b4)
    return concat


def Inception_v1_NoAuxClassifier(class_num: int = 1000,
                                 has_dropout: bool = True) -> Sequential:
    model = Sequential()
    _conv_relu(model, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1"))
    _conv_relu(model, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_relu(model, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2"))

    model.add(Inception_Layer_v1(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/"))
    model.add(Inception_Layer_v1(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool3/3x3_s2"))
    model.add(Inception_Layer_v1(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/"))
    model.add(Inception_Layer_v1(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/"))
    model.add(Inception_Layer_v1(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/"))
    model.add(Inception_Layer_v1(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/"))
    model.add(Inception_Layer_v1(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool4/3x3_s2"))
    model.add(Inception_Layer_v1(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/"))
    model.add(Inception_Layer_v1(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/"))
    model.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        model.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    model.add(Reshape([1024], batch_mode=True))
    model.add(
        Linear(1024, class_num, init_weight=Xavier(), init_bias=Zeros())
        .set_name("loss3/classifier")
    )
    model.add(LogSoftMax().set_name("loss3/loss3"))
    return model


def _aux_head(input_size: int, class_num: int, name_prefix: str) -> Sequential:
    """GoogLeNet auxiliary classifier: 5x5/3 avgpool → 1x1 conv(128) →
    fc(1024) → Dropout(0.7) → fc(classes) → LogSoftMax (reference
    ``Inception.scala`` — loss1/loss2 towers)."""
    s = Sequential()
    s.add(SpatialAveragePooling(5, 5, 3, 3).set_name(name_prefix + "ave_pool"))
    _conv_relu(s, input_size, 128, 1, 1, name=name_prefix + "conv")
    s.add(Reshape([128 * 4 * 4], batch_mode=True))
    s.add(Linear(128 * 4 * 4, 1024, init_weight=Xavier(), init_bias=Zeros())
          .set_name(name_prefix + "fc"))
    s.add(ReLU(True))
    s.add(Dropout(0.7).set_name(name_prefix + "drop_fc"))
    s.add(Linear(1024, class_num, init_weight=Xavier(), init_bias=Zeros())
          .set_name(name_prefix + "classifier"))
    s.add(LogSoftMax().set_name(name_prefix + "loss"))
    return s


def Inception_v1(class_num: int = 1000, has_dropout: bool = True) -> Sequential:
    """Training GoogLeNet WITH the two auxiliary classifiers (reference
    ``Inception.scala`` — ``Inception_v1``). Output is a flat table
    ``[main, aux@4d, aux@4a]``; train with
    ``ParallelCriterion(repeat_target=True).add(ClassNLLCriterion(), 1.0)
    .add(ClassNLLCriterion(), 0.3).add(ClassNLLCriterion(), 0.3)``."""
    from bigdl_tpu.nn import ConcatTable, FlattenTable

    feature1 = Sequential()
    _conv_relu(feature1, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2")
    feature1.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
    feature1.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1"))
    _conv_relu(feature1, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_relu(feature1, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    feature1.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2"))
    feature1.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2"))
    feature1.add(Inception_Layer_v1(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/"))
    feature1.add(Inception_Layer_v1(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/"))
    feature1.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool3/3x3_s2"))
    feature1.add(Inception_Layer_v1(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/"))

    output1 = _aux_head(512, class_num, "loss1/")

    feature2 = Sequential()
    feature2.add(Inception_Layer_v1(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/"))
    feature2.add(Inception_Layer_v1(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/"))
    feature2.add(Inception_Layer_v1(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/"))

    output2 = _aux_head(528, class_num, "loss2/")

    output3 = Sequential()
    output3.add(Inception_Layer_v1(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/"))
    output3.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool4/3x3_s2"))
    output3.add(Inception_Layer_v1(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/"))
    output3.add(Inception_Layer_v1(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/"))
    output3.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        output3.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    output3.add(Reshape([1024], batch_mode=True))
    output3.add(Linear(1024, class_num, init_weight=Xavier(), init_bias=Zeros())
                .set_name("loss3/classifier"))
    output3.add(LogSoftMax().set_name("loss3/loss3"))

    main = Sequential().add(feature2).add(
        ConcatTable().add(output3).add(output2))
    model = Sequential().add(feature1).add(
        ConcatTable().add(main).add(output1)).add(FlattenTable())
    return model


# ---------------------------------------------------------------------------
# Inception-v2 (BN-Inception, Ioffe & Szegedy 2015)
# ---------------------------------------------------------------------------

def _conv_bn_relu(seq: Sequential, n_in, n_out, kw, kh, sw=1, sh=1,
                  pw=0, ph=0, name: str = "") -> Sequential:
    from bigdl_tpu.nn import SpatialBatchNormalization

    seq.add(SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                               init_weight=Xavier(), init_bias=Zeros())
            .set_name(name))
    seq.add(SpatialBatchNormalization(n_out, 1e-3).set_name(name + "/bn"))
    seq.add(ReLU(True))
    return seq


def Inception_Layer_v2(input_size: int, config, name_prefix: str = "") -> Concat:
    """BN-inception block (reference ``Inception.scala`` —
    ``Inception_Layer_v2``): branches 1x1 | 1x1→3x3 | 1x1→3x3→3x3 (double) |
    pool→proj, every conv followed by BatchNorm+ReLU. ``config[0][0] == 0``
    marks a stride-2 reduction block (no 1x1 branch, un-projected maxpool);
    ``config[3]`` is ``(pool_type, proj)`` with pool_type "avg"|"max"."""
    c = [list(branch) for branch in config]
    out1 = int(c[0][0])
    stride2 = out1 == 0
    s = 2 if stride2 else 1
    concat = Concat(2)

    if not stride2:
        concat.add(_conv_bn_relu(Sequential(), input_size, out1, 1, 1,
                                 name=name_prefix + "1x1"))

    r3, o3 = c[1]
    b2 = _conv_bn_relu(Sequential(), input_size, r3, 1, 1,
                       name=name_prefix + "3x3_reduce")
    _conv_bn_relu(b2, r3, o3, 3, 3, s, s, 1, 1, name=name_prefix + "3x3")
    concat.add(b2)

    rd, od = c[2]
    b3 = _conv_bn_relu(Sequential(), input_size, rd, 1, 1,
                       name=name_prefix + "double3x3_reduce")
    _conv_bn_relu(b3, rd, od, 3, 3, 1, 1, 1, 1, name=name_prefix + "double3x3a")
    _conv_bn_relu(b3, od, od, 3, 3, s, s, 1, 1, name=name_prefix + "double3x3b")
    concat.add(b3)

    pool_type, proj = c[3][0], int(c[3][1])
    b4 = Sequential()
    if stride2:
        b4.add(SpatialMaxPooling(3, 3, 2, 2).ceil()
               .set_name(name_prefix + "pool"))
    elif pool_type == "avg":
        b4.add(SpatialAveragePooling(3, 3, 1, 1, 1, 1, ceil_mode=True)
               .set_name(name_prefix + "pool"))
    else:
        b4.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()
               .set_name(name_prefix + "pool"))
    if proj:
        _conv_bn_relu(b4, input_size, proj, 1, 1,
                      name=name_prefix + "pool_proj")
    concat.add(b4)
    return concat


def Inception_v2(class_num: int = 1000) -> Sequential:
    """BN-Inception main tower (reference ``Inception.scala`` —
    ``Inception_v2``); the standard BN-GoogLeNet config table."""
    model = Sequential()
    _conv_bn_relu(model, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
    _conv_bn_relu(model, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_bn_relu(model, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2"))

    model.add(Inception_Layer_v2(192, [[64], [64, 64], [64, 96], ["avg", 32]], "inception_3a/"))
    model.add(Inception_Layer_v2(256, [[64], [64, 96], [64, 96], ["avg", 64]], "inception_3b/"))
    model.add(Inception_Layer_v2(320, [[0], [128, 160], [64, 96], ["max", 0]], "inception_3c/"))
    model.add(Inception_Layer_v2(576, [[224], [64, 96], [96, 128], ["avg", 128]], "inception_4a/"))
    model.add(Inception_Layer_v2(576, [[192], [96, 128], [96, 128], ["avg", 128]], "inception_4b/"))
    model.add(Inception_Layer_v2(576, [[160], [128, 160], [128, 160], ["avg", 96]], "inception_4c/"))
    model.add(Inception_Layer_v2(576, [[96], [128, 192], [160, 192], ["avg", 96]], "inception_4d/"))
    model.add(Inception_Layer_v2(576, [[0], [128, 192], [192, 256], ["max", 0]], "inception_4e/"))
    model.add(Inception_Layer_v2(1024, [[352], [192, 320], [160, 224], ["avg", 128]], "inception_5a/"))
    model.add(Inception_Layer_v2(1024, [[352], [192, 320], [192, 224], ["max", 128]], "inception_5b/"))
    model.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    model.add(Reshape([1024], batch_mode=True))
    model.add(Linear(1024, class_num, init_weight=Xavier(), init_bias=Zeros())
              .set_name("loss3/classifier"))
    model.add(LogSoftMax().set_name("loss3/loss3"))
    return model


def train_main(argv=None):
    """Reference ``models/inception/TrainInceptionV1.scala`` main
    (BASELINE target #4; poly LR decay)."""
    from bigdl_tpu.models.utils import (
        run_training, synthetic_imagenet_samples, train_parser,
    )
    from bigdl_tpu.nn.criterion import ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import SGD, Poly

    args = train_parser("Inception-v1 on ImageNet",
                        batch_size=64, learning_rate=0.01,
                        max_epoch=2).parse_args(argv)
    if args.folder:
        from bigdl_tpu.dataset.image import image_folder_samples

        samples = image_folder_samples(args.folder, image_size=224)
    else:
        samples = synthetic_imagenet_samples(args.synthetic)
    method = SGD(learning_rate=args.learningRate, momentum=args.momentum,
                 weight_decay=args.weightDecay,
                 learning_rate_schedule=Poly(0.5, 62000))
    return run_training(Inception_v1_NoAuxClassifier(1000), samples,
                        ClassNLLCriterion(), args, optim_method=method)


if __name__ == "__main__":
    train_main()
