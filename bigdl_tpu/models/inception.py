"""Inception-v1 (GoogLeNet) — BASELINE config #4 (ImageNet, poly LR).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/inception/
Inception.scala`` — ``Inception_v1(classNum)`` / ``Inception_v1_NoAuxClassifier``;
inception blocks are a 4-way ``Concat(2)`` (1x1 | 1x1→3x3 | 1x1→5x5 |
maxpool→1x1), stem is 7x7/2 conv → maxpool(ceil) → LRN → 1x1 → 3x3 → LRN →
maxpool, head is 7x7 avgpool → Dropout(0.4) → Linear(1024, classNum) →
LogSoftMax. Xavier init throughout.

TPU-native notes: the four branches are independent convs over the same
input — XLA schedules them back-to-back on the MXU and the ``Concat`` is a
layout no-op folded into the next conv's operand. Ceil-mode pooling maps to
explicit -inf padding in ``lax.reduce_window``.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Concat, Dropout, Linear, LogSoftMax, ReLU, Reshape, Sequential,
    SpatialAveragePooling, SpatialConvolution, SpatialCrossMapLRN,
    SpatialMaxPooling, Xavier, Zeros,
)


def _conv_relu(seq: Sequential, n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0,
               name: str = "") -> Sequential:
    """Append Xavier-init conv + in-place ReLU to ``seq`` (every conv in this
    net uses exactly this pattern)."""
    seq.add(
        SpatialConvolution(
            n_in, n_out, kw, kh, sw, sh, pw, ph,
            init_weight=Xavier(), init_bias=Zeros(),
        ).set_name(name)
    )
    seq.add(ReLU(True))
    return seq


def Inception_Layer_v1(input_size: int, config, name_prefix: str = "") -> Concat:
    """One inception block. ``config`` is reference-style:
    ``T(T(out1x1), T(reduce3x3, out3x3), T(reduce5x5, out5x5), T(pool_proj))``
    — accepted here as a nested list/tuple."""
    c = [list(branch) for branch in config]
    concat = Concat(2)

    b1 = _conv_relu(Sequential(), input_size, c[0][0], 1, 1,
                    name=name_prefix + "1x1")
    concat.add(b1)

    b2 = _conv_relu(Sequential(), input_size, c[1][0], 1, 1,
                    name=name_prefix + "3x3_reduce")
    _conv_relu(b2, c[1][0], c[1][1], 3, 3, 1, 1, 1, 1,
               name=name_prefix + "3x3")
    concat.add(b2)

    b3 = _conv_relu(Sequential(), input_size, c[2][0], 1, 1,
                    name=name_prefix + "5x5_reduce")
    _conv_relu(b3, c[2][0], c[2][1], 5, 5, 1, 1, 2, 2,
               name=name_prefix + "5x5")
    concat.add(b3)

    b4 = Sequential()
    b4.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil().set_name(name_prefix + "pool"))
    _conv_relu(b4, input_size, c[3][0], 1, 1, name=name_prefix + "pool_proj")
    concat.add(b4)
    return concat


def Inception_v1_NoAuxClassifier(class_num: int = 1000,
                                 has_dropout: bool = True) -> Sequential:
    model = Sequential()
    _conv_relu(model, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1"))
    _conv_relu(model, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_relu(model, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2"))

    model.add(Inception_Layer_v1(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/"))
    model.add(Inception_Layer_v1(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool3/3x3_s2"))
    model.add(Inception_Layer_v1(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/"))
    model.add(Inception_Layer_v1(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/"))
    model.add(Inception_Layer_v1(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/"))
    model.add(Inception_Layer_v1(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/"))
    model.add(Inception_Layer_v1(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool4/3x3_s2"))
    model.add(Inception_Layer_v1(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/"))
    model.add(Inception_Layer_v1(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/"))
    model.add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        model.add(Dropout(0.4).set_name("pool5/drop_7x7_s1"))
    model.add(Reshape([1024], batch_mode=True))
    model.add(
        Linear(1024, class_num, init_weight=Xavier(), init_bias=Zeros())
        .set_name("loss3/classifier")
    )
    model.add(LogSoftMax().set_name("loss3/loss3"))
    return model


# The aux-classifier training variant shares the same main tower; the two
# auxiliary heads only change the training loss. Parity alias:
Inception_v1 = Inception_v1_NoAuxClassifier


def train_main(argv=None):
    """Reference ``models/inception/TrainInceptionV1.scala`` main
    (BASELINE target #4; poly LR decay)."""
    from bigdl_tpu.models.utils import (
        run_training, synthetic_imagenet_samples, train_parser,
    )
    from bigdl_tpu.nn.criterion import ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import SGD, Poly

    args = train_parser("Inception-v1 on ImageNet",
                        batch_size=64, learning_rate=0.01,
                        max_epoch=2).parse_args(argv)
    if args.folder:
        from bigdl_tpu.dataset.image import image_folder_samples

        samples = image_folder_samples(args.folder, image_size=224)
    else:
        samples = synthetic_imagenet_samples(args.synthetic)
    method = SGD(learning_rate=args.learningRate, momentum=args.momentum,
                 weight_decay=args.weightDecay,
                 learning_rate_schedule=Poly(0.5, 62000))
    return run_training(Inception_v1_NoAuxClassifier(1000), samples,
                        ClassNLLCriterion(), args, optim_method=method)


if __name__ == "__main__":
    train_main()
