"""ResNet — BASELINE config #3 (ResNet-50 / ImageNet / SGD + step LR).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/resnet/ResNet.scala``
— ``ResNet(classNum, T("shortcutType" -> "B", "depth" -> 50, ...))`` builds a
Graph of conv-BN blocks with MSRA init; CIFAR-10 depths are ``6n+2`` basic
blocks over 16/32/64 planes, ImageNet depths 18/34 (basic) and 50/101/152
(bottleneck) over 64..512 planes with expansion 4; shortcut type A =
padded identity, B = 1x1-conv projection on dimension change, C = always
projection. ``TrainImageNet`` additionally zero-initializes the last BN gamma
of every residual block ("zero gamma") and uses no-bias convolutions.

TPU-native notes: the whole Graph traces into one XLA program; residual adds
fuse into the preceding conv epilogues, and the 7x7/stride-2 stem + 3x3 convs
hit the MXU's native convolution path (no im2col). Shortcut type A is a
strided slice + channel zero-pad, which XLA folds into a cheap pad op.
"""

from __future__ import annotations

from typing import Dict, Optional

from bigdl_tpu.nn import (
    CAddTable, Graph, Input, Linear, LogSoftMax, MsraFiller, ReLU, Reshape,
    Sequential, SpatialAveragePooling, SpatialBatchNormalization,
    SpatialConvolution, SpatialMaxPooling, Xavier, Zeros,
)
from bigdl_tpu.nn.module import TensorModule


class _PaddedShortcut(TensorModule):
    """Type-A shortcut: stride the identity spatially and zero-pad channels
    (reference ResNet.scala shortcut ``shortcutType == "A"`` — a
    SpatialAveragePooling(1,1,stride,stride) + Concat with zero tensor; here
    a strided slice + lax.pad, identical math, one XLA op)."""

    def __init__(self, n_in: int, n_out: int, stride: int) -> None:
        super().__init__()
        self.n_in = n_in
        self.n_out = n_out
        self.stride = stride

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        x = input[:, :, :: self.stride, :: self.stride]
        if self.n_out > self.n_in:
            pad = self.n_out - self.n_in
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return x, state


def _conv(n_in, n_out, k, stride=1, pad=0):
    """conv(no bias) → BN → handled by caller; MSRA weight init as in
    ``ResNet.modelInit``."""
    return SpatialConvolution(
        n_in, n_out, k, k, stride, stride, pad, pad, with_bias=False,
        init_weight=MsraFiller(False),
    )


def _bn(n, zero_gamma=False):
    bn = SpatialBatchNormalization(n)
    if zero_gamma:
        bn.set_init_method(weight_init=Zeros())
    return bn


def _shortcut(n_in: int, n_out: int, stride: int, shortcut_type: str):
    use_conv = shortcut_type == "C" or (shortcut_type == "B" and n_in != n_out)
    if use_conv:
        return (
            Sequential()
            .add(_conv(n_in, n_out, 1, stride))
            .add(_bn(n_out))
        )
    if n_in != n_out or stride != 1:
        return _PaddedShortcut(n_in, n_out, stride)
    return None  # identity


def _basic_block(n_in, planes, stride, zero_gamma):
    residual = (
        Sequential()
        .add(_conv(n_in, planes, 3, stride, 1))
        .add(_bn(planes))
        .add(ReLU(True))
        .add(_conv(planes, planes, 3, 1, 1))
        .add(_bn(planes, zero_gamma))
    )
    return residual, planes


def _bottleneck_block(n_in, planes, stride, zero_gamma):
    n_out = planes * 4
    residual = (
        Sequential()
        .add(_conv(n_in, planes, 1))
        .add(_bn(planes))
        .add(ReLU(True))
        .add(_conv(planes, planes, 3, stride, 1))
        .add(_bn(planes))
        .add(ReLU(True))
        .add(_conv(planes, n_out, 1))
        .add(_bn(n_out, zero_gamma))
    )
    return residual, n_out


def _residual(node, n_in, planes, stride, block_fn, shortcut_type, zero_gamma):
    """residual(x) + shortcut(x) → ReLU, as a Graph sub-DAG."""
    residual, n_out = block_fn(n_in, planes, stride, zero_gamma)
    res_node = residual.inputs(node)
    sc = _shortcut(n_in, n_out, stride, shortcut_type)
    sc_node = node if sc is None else sc.inputs(node)
    add = CAddTable().inputs(res_node, sc_node)
    out = ReLU(True).inputs(add)
    return out, n_out


_IMAGENET_CFG: Dict[int, tuple] = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
    200: ("bottleneck", (3, 24, 36, 3)),
}


def ResNet(class_num: int = 1000, opt: Optional[dict] = None) -> Graph:
    """Reference-compatible entry: ``ResNet(classNum, T("depth" -> 50,
    "shortcutType" -> "B", "dataSet" -> "ImageNet"))``."""
    opt = dict(opt or {})
    depth = int(opt.get("depth", 50))
    shortcut_type = str(opt.get("shortcutType", opt.get("shortcut_type", "B")))
    dataset = str(opt.get("dataSet", opt.get("dataset", "ImageNet")))
    zero_gamma = bool(opt.get("zeroGamma", opt.get("zero_gamma", True)))

    if dataset.lower() == "cifar10":
        return _resnet_cifar(class_num, depth, shortcut_type, zero_gamma)
    return _resnet_imagenet(class_num, depth, shortcut_type, zero_gamma)


def _resnet_imagenet(class_num, depth, shortcut_type, zero_gamma) -> Graph:
    if depth not in _IMAGENET_CFG:
        raise ValueError(f"unsupported ImageNet ResNet depth {depth}")
    kind, counts = _IMAGENET_CFG[depth]
    block_fn = _basic_block if kind == "basic" else _bottleneck_block

    inp = Input()
    x = SpatialConvolution(
        3, 64, 7, 7, 2, 2, 3, 3, with_bias=False, init_weight=MsraFiller(False)
    ).inputs(inp)
    x = _bn(64).inputs(x)
    x = ReLU(True).inputs(x)
    x = SpatialMaxPooling(3, 3, 2, 2, 1, 1).inputs(x)

    n_in = 64
    for stage, (planes, count) in enumerate(zip((64, 128, 256, 512), counts)):
        for i in range(count):
            stride = 2 if (stage > 0 and i == 0) else 1
            x, n_in = _residual(
                x, n_in, planes, stride, block_fn, shortcut_type, zero_gamma
            )

    x = SpatialAveragePooling(7, 7, 1, 1).inputs(x)
    x = Reshape([n_in], batch_mode=True).inputs(x)
    out = Linear(
        n_in, class_num, init_weight=Xavier(), init_bias=Zeros()
    ).inputs(x)
    return Graph(inp, out)


def _resnet_cifar(class_num, depth, shortcut_type, zero_gamma) -> Graph:
    if (depth - 2) % 6 != 0:
        raise ValueError("CIFAR ResNet depth must be 6n+2 (20, 32, 44, 56, 110)")
    n = (depth - 2) // 6

    inp = Input()
    x = _conv(3, 16, 3, 1, 1).inputs(inp)
    x = _bn(16).inputs(x)
    x = ReLU(True).inputs(x)

    n_in = 16
    for stage, planes in enumerate((16, 32, 64)):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            x, n_in = _residual(
                x, n_in, planes, stride, _basic_block, shortcut_type, zero_gamma
            )

    x = SpatialAveragePooling(8, 8, 1, 1).inputs(x)
    x = Reshape([64], batch_mode=True).inputs(x)
    x = Linear(64, class_num, init_weight=Xavier(), init_bias=Zeros()).inputs(x)
    out = LogSoftMax().inputs(x)
    return Graph(inp, out)


def train_main(argv=None):
    """Reference ``models/resnet/TrainImageNet.scala`` /
    ``TrainCIFAR10.scala`` mains (BASELINE target #3). ``--dataset``
    selects imagenet (synthetic unless -f) or cifar10."""
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD

    p = train_parser("ResNet training", batch_size=128,
                     learning_rate=0.1, max_epoch=10)
    p.add_argument("--dataset", default="cifar10",
                   choices=["cifar10", "imagenet"])
    p.add_argument("--depth", type=int, default=None,
                   help="default: 20 (cifar10) / 50 (imagenet)")
    p.add_argument("--warmupEpoch", type=int, default=0)
    args = p.parse_args(argv)

    if args.dataset == "cifar10":
        from bigdl_tpu.dataset.cifar import load_samples

        samples = load_samples(args.folder or "/nonexistent", "train",
                               synthetic_count=args.synthetic)
        model = ResNet(10, {"depth": args.depth or 20, "shortcutType": "A",
                            "dataSet": "cifar10"})
    else:
        from bigdl_tpu.models.utils import synthetic_imagenet_samples

        if args.folder:
            from bigdl_tpu.dataset.image import image_folder_samples

            samples = image_folder_samples(args.folder, image_size=224)
        else:
            samples = synthetic_imagenet_samples(args.synthetic)
        model = ResNet(1000, {"depth": args.depth or 50, "shortcutType": "B"})
    method = SGD(learning_rate=args.learningRate, momentum=args.momentum,
                 weight_decay=args.weightDecay, nesterov=True)
    return run_training(model, samples, CrossEntropyCriterion(), args,
                        optim_method=method)


if __name__ == "__main__":
    train_main()
