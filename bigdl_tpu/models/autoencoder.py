"""Autoencoder — MNIST MLP autoencoder from the reference zoo.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/autoencoder/
Autoencoder.scala`` — ``Autoencoder(classNum=32)``: 784 → hidden (ReLU) →
784 (Sigmoid), trained with MSECriterion against the input.
"""

from __future__ import annotations

from bigdl_tpu.nn import Linear, ReLU, Reshape, Sequential, Sigmoid


def Autoencoder(class_num: int = 32) -> Sequential:
    row_n, col_n = 28, 28
    feature_size = row_n * col_n
    return (
        Sequential()
        .add(Reshape([feature_size]))
        .add(Linear(feature_size, class_num))
        .add(ReLU(True))
        .add(Linear(class_num, feature_size))
        .add(Sigmoid())
    )
