"""Autoencoder — MNIST MLP autoencoder from the reference zoo.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/autoencoder/
Autoencoder.scala`` — ``Autoencoder(classNum=32)``: 784 → hidden (ReLU) →
784 (Sigmoid), trained with MSECriterion against the input.
"""

from __future__ import annotations

from bigdl_tpu.nn import Linear, ReLU, Reshape, Sequential, Sigmoid


def Autoencoder(class_num: int = 32) -> Sequential:
    row_n, col_n = 28, 28
    feature_size = row_n * col_n
    return (
        Sequential()
        .add(Reshape([feature_size]))
        .add(Linear(feature_size, class_num))
        .add(ReLU(True))
        .add(Linear(class_num, feature_size))
        .add(Sigmoid())
    )


def train_main(argv=None):
    """Reference ``models/autoencoder`` Train main (MNIST reconstruction,
    MSE; synthetic digits unless ``-f``)."""
    import numpy as np

    from bigdl_tpu.dataset.mnist import load_samples
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import MSECriterion
    from bigdl_tpu.optim.optim_method import Adagrad

    args = train_parser("Autoencoder on MNIST", batch_size=128,
                        learning_rate=0.01, max_epoch=2).parse_args(argv)
    base = load_samples(args.folder or "/nonexistent", "train",
                        synthetic_count=args.synthetic)
    # reconstruction task: target = the flattened input itself
    samples = [Sample(np.asarray(s.features[0]).reshape(-1),
                      np.asarray(s.features[0]).reshape(-1)) for s in base]
    return run_training(Autoencoder(32), samples, MSECriterion(), args,
                        optim_method=Adagrad(learning_rate=args.learningRate))


if __name__ == "__main__":
    train_main()
