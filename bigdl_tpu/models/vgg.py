"""VGG — BASELINE config #2 (VGG-16 / CIFAR-10 / DistriOptimizer).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/vgg/`` —
``VggForCifar10`` is the conv-BN-ReLU variant ending in two 512-wide FC
layers with BN + Dropout and LogSoftMax; ``Vgg_16``/``Vgg_19`` are the plain
ImageNet towers (no BN, 4096-wide FCs).

TPU-native notes: all convs are 3x3 stride-1 — the best possible shape for
the MXU; BN and ReLU fuse into the conv epilogue under XLA, so the
conv-BN-ReLU triple costs one fused kernel per layer.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    BatchNormalization, Dropout, Linear, LogSoftMax, ReLU, Reshape, Sequential,
    SpatialBatchNormalization, SpatialConvolution, SpatialMaxPooling,
)


def VggForCifar10(class_num: int = 10, has_dropout: bool = True) -> Sequential:
    model = Sequential()

    def conv_bn_relu(n_in, n_out):
        model.add(SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
        model.add(SpatialBatchNormalization(n_out, 1e-3))
        model.add(ReLU(True))

    conv_bn_relu(3, 64)
    if has_dropout:
        model.add(Dropout(0.3))
    conv_bn_relu(64, 64)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(64, 128)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(128, 128)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(128, 256)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(256, 256)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(256, 256)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(256, 512)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(512, 512)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    if has_dropout:
        model.add(Dropout(0.4))
    conv_bn_relu(512, 512)
    model.add(SpatialMaxPooling(2, 2, 2, 2).ceil())

    model.add(Reshape([512], batch_mode=True))
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(512, 512))
    model.add(BatchNormalization(512))
    model.add(ReLU(True))
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(512, class_num))
    model.add(LogSoftMax())
    return model


def _vgg_tower(cfg, class_num: int, has_dropout: bool = True) -> Sequential:
    model = Sequential()
    n_in = 3
    for item in cfg:
        if item == "M":
            model.add(SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(SpatialConvolution(n_in, item, 3, 3, 1, 1, 1, 1))
            model.add(ReLU(True))
            n_in = item
    model.add(Reshape([512 * 7 * 7], batch_mode=True))
    model.add(Linear(512 * 7 * 7, 4096))
    model.add(ReLU(True))
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(4096, 4096))
    model.add(ReLU(True))
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(4096, class_num))
    model.add(LogSoftMax())
    return model


def Vgg_16(class_num: int = 1000, has_dropout: bool = True) -> Sequential:
    return _vgg_tower(
        [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
        class_num, has_dropout,
    )


def Vgg_19(class_num: int = 1000, has_dropout: bool = True) -> Sequential:
    return _vgg_tower(
        [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
        class_num, has_dropout,
    )


def train_main(argv=None):
    """Reference ``models/vgg/Train.scala`` main (BASELINE target #2 —
    VGG/CIFAR-10 via DistriOptimizer; single-chip here, DP on a mesh)."""
    from bigdl_tpu.dataset.cifar import load_samples
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import ClassNLLCriterion

    args = train_parser("VGG on CIFAR-10", batch_size=128,
                        learning_rate=0.01, max_epoch=10).parse_args(argv)
    samples = load_samples(args.folder or "/nonexistent", "train",
                           synthetic_count=args.synthetic)
    return run_training(VggForCifar10(10), samples, ClassNLLCriterion(), args)


def test_main(argv=None):
    from bigdl_tpu.dataset.cifar import load_samples
    from bigdl_tpu.models.utils import run_test, test_parser

    args = test_parser("VGG CIFAR-10 evaluation").parse_args(argv)
    samples = load_samples(args.folder or "/nonexistent", "test",
                           synthetic_count=args.synthetic)
    return run_test(args.model, samples, args.batchSize)


if __name__ == "__main__":
    train_main()
