"""BiRecurrent LSTM text classifier — BASELINE config #5.

Reference (UNVERIFIED, SURVEY.md §0):
``pyspark/bigdl/models/textclassifier/textclassifier.py`` and
``.../bigdl/example/textclassification/TextClassifier.scala`` — GloVe
embeddings + ``Recurrent``/``BiRecurrent`` LSTM over the sequence, last
hidden state → ``Linear`` → ``LogSoftMax``.

Two fronts are provided, matching the reference's two pipelines:
* ``embedding_input=True`` (reference default): the host pipeline already
  embedded tokens (GloVe); input is ``(batch, seq, embedding_dim)`` floats.
* ``embedding_input=False``: a trainable ``LookupTable`` front; input is
  ``(batch, seq)`` of 1-based word ids (0 = padding → zero vector), as
  produced by ``bigdl_tpu.dataset.text.SentenceToWordIndices``.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn import (
    BiRecurrent, Linear, LogSoftMax, LookupTable, LSTM, Recurrent, Select,
    Sequential, TensorModule,
)


class _BiEnds(TensorModule):
    """(B, T, 2H) bidirectional output → (B, 2H) summary: forward half's
    LAST step ‖ backward half's FIRST step — the two positions where each
    direction has consumed the whole sequence (a reversed Recurrent stores
    step outputs at their original time index, so its full-sequence state
    sits at t=0, not t=T-1)."""

    def __init__(self, hidden_size: int) -> None:
        super().__init__()
        self.hidden_size = hidden_size

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        h = self.hidden_size
        return jnp.concatenate([input[:, -1, :h], input[:, 0, h:]], axis=-1), state


def TextClassifier(class_num: int, embedding_dim: int = 200,
                   hidden_size: int = 128, vocab_size: Optional[int] = None,
                   embedding_input: bool = True,
                   bidirectional: bool = True) -> Sequential:
    model = Sequential()
    if not embedding_input:
        if vocab_size is None:
            raise ValueError("vocab_size is required with embedding_input=False")
        model.add(LookupTable(vocab_size, embedding_dim))
    if bidirectional:
        model.add(BiRecurrent(merge="concat").add(LSTM(embedding_dim, hidden_size)))
        model.add(_BiEnds(hidden_size))
        feat = 2 * hidden_size
    else:
        model.add(Recurrent().add(LSTM(embedding_dim, hidden_size)))
        model.add(Select(2, -1))  # last timestep (1-based dim 2 = time)
        feat = hidden_size
    model.add(Linear(feat, class_num))
    model.add(LogSoftMax())
    return model
