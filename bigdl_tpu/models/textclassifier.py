"""BiRecurrent LSTM text classifier — BASELINE config #5.

Reference (UNVERIFIED, SURVEY.md §0):
``pyspark/bigdl/models/textclassifier/textclassifier.py`` and
``.../bigdl/example/textclassification/TextClassifier.scala`` — GloVe
embeddings + ``Recurrent``/``BiRecurrent`` LSTM over the sequence, last
hidden state → ``Linear`` → ``LogSoftMax``.

Two fronts are provided, matching the reference's two pipelines:
* ``embedding_input=True`` (reference default): the host pipeline already
  embedded tokens (GloVe); input is ``(batch, seq, embedding_dim)`` floats.
* ``embedding_input=False``: a trainable ``LookupTable`` front; input is
  ``(batch, seq)`` of 1-based word ids (0 = padding → zero vector), as
  produced by ``bigdl_tpu.dataset.text.SentenceToWordIndices``.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn import (
    BiRecurrent, Linear, LogSoftMax, LookupTable, LSTM, Recurrent, Select,
    Sequential, TensorModule,
)


class _BiEnds(TensorModule):
    """(B, T, 2H) bidirectional output → (B, 2H) summary: forward half's
    LAST step ‖ backward half's FIRST step — the two positions where each
    direction has consumed the whole sequence (a reversed Recurrent stores
    step outputs at their original time index, so its full-sequence state
    sits at t=0, not t=T-1)."""

    def __init__(self, hidden_size: int) -> None:
        super().__init__()
        self.hidden_size = hidden_size

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        h = self.hidden_size
        return jnp.concatenate([input[:, -1, :h], input[:, 0, h:]], axis=-1), state


def TextClassifier(class_num: int, embedding_dim: int = 200,
                   hidden_size: int = 128, vocab_size: Optional[int] = None,
                   embedding_input: bool = True,
                   bidirectional: bool = True) -> Sequential:
    model = Sequential()
    if not embedding_input:
        if vocab_size is None:
            raise ValueError("vocab_size is required with embedding_input=False")
        model.add(LookupTable(vocab_size, embedding_dim))
    if bidirectional:
        model.add(BiRecurrent(merge="concat").add(LSTM(embedding_dim, hidden_size)))
        model.add(_BiEnds(hidden_size))
        feat = 2 * hidden_size
    else:
        model.add(Recurrent().add(LSTM(embedding_dim, hidden_size)))
        model.add(Select(2, -1))  # last timestep (1-based dim 2 = time)
        feat = hidden_size
    model.add(Linear(feat, class_num))
    model.add(LogSoftMax())
    return model


def train_main(argv=None):
    """Reference ``example/textclassification/TextClassifier.scala`` /
    pyspark ``textclassifier.py`` main (BASELINE target #5 — BiRecurrent
    LSTM). ``-f`` = news20-style directory (one subdir per class holding
    ``.txt`` files); synthetic token sequences otherwise. Both use the
    LookupTable path (no pretrained GloVe embeddings in this image)."""
    import os

    import numpy as np

    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import Dictionary, simple_tokenize
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import Adagrad

    p = train_parser("BiRecurrent LSTM text classifier", batch_size=32,
                     learning_rate=0.05, max_epoch=3)
    p.add_argument("--vocab", type=int, default=500)
    p.add_argument("--seqLen", type=int, default=50)
    p.add_argument("--classNum", type=int, default=5)
    p.add_argument("--embeddingDim", type=int, default=64)
    p.add_argument("--news20", action="store_true",
                   help="use the news20 + GloVe pipeline (the reference's "
                        "default: pre-embedded input, no LookupTable)")
    args = p.parse_args(argv)

    rng = np.random.default_rng(0)
    samples = []
    vocab, class_num = args.vocab, args.classNum
    if args.news20:
        # reference pyspark textclassifier.py pipeline: tokenize → GloVe
        # embed on the host → (seq, dim) float features
        from bigdl_tpu.dataset.news20 import get_news20, glove_dict

        data_dir = args.folder or "/tmp/news20"
        texts = get_news20(data_dir)
        w2v = glove_dict(source_dir=os.path.join(data_dir, "glove.6B"),
                         dim=args.embeddingDim)
        zero = np.zeros((args.embeddingDim,), np.float32)
        class_num = max(l for _, l in texts)
        for text, label in texts:
            toks = simple_tokenize(text)[: args.seqLen]
            mat = np.stack([w2v.get(t, zero) for t in toks]) if toks else \
                np.zeros((1, args.embeddingDim), np.float32)
            if mat.shape[0] < args.seqLen:
                mat = np.concatenate(
                    [mat, np.zeros((args.seqLen - mat.shape[0],
                                    args.embeddingDim), np.float32)])
            samples.append(Sample(mat.astype(np.float32), np.int32(label)))
        model = TextClassifier(class_num, embedding_dim=args.embeddingDim,
                               embedding_input=True)
        return run_training(model, samples, ClassNLLCriterion(), args,
                            optim_method=Adagrad(
                                learning_rate=args.learningRate))
    if args.folder:
        classes = sorted(d for d in os.listdir(args.folder)
                         if os.path.isdir(os.path.join(args.folder, d)))
        if not classes:
            raise ValueError(f"{args.folder}: no class subdirectories")
        docs = []
        for ci, cls in enumerate(classes):
            cdir = os.path.join(args.folder, cls)
            for fn in sorted(os.listdir(cdir)):
                with open(os.path.join(cdir, fn), errors="ignore") as f:
                    docs.append((simple_tokenize(f.read()), ci + 1))
        d = Dictionary([t for t, _ in docs])
        vocab, class_num = d.vocab_size(), len(classes)
        for toks, label in docs:
            ids = [d.get_index(t) + 1 for t in toks][: args.seqLen]
            ids += [1] * (args.seqLen - len(ids))  # pad with id 1
            samples.append(Sample(np.asarray(ids, np.float32),
                                  np.int32(label)))
    else:
        for _ in range(args.synthetic):
            c = int(rng.integers(1, class_num + 1))
            # class-dependent token distribution so the task is learnable
            base = (c - 1) * (vocab // class_num)
            toks = rng.integers(base + 1, base + vocab // class_num + 1,
                                size=(args.seqLen,))
            samples.append(Sample(toks.astype(np.float32), np.int32(c)))
    model = TextClassifier(class_num, embedding_dim=args.embeddingDim,
                           vocab_size=vocab, embedding_input=False)
    return run_training(model, samples, ClassNLLCriterion(), args,
                        optim_method=Adagrad(learning_rate=args.learningRate))


if __name__ == "__main__":
    train_main()
