"""PTB language model — the reference's ``models/rnn`` zoo member.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/rnn/PTBModel.scala``
— ``LookupTable`` → stacked LSTM ``Recurrent`` layers → per-timestep
``Linear`` → ``LogSoftMax``; trained with ``TimeDistributedCriterion(
ClassNLLCriterion)`` over next-word targets. ``SimpleRNN`` is the
``RnnCell``-based variant from the same directory.

TPU-native notes: each LSTM layer is one ``lax.scan``; the output projection
runs on the folded ``(B·T, H)`` matrix (one MXU gemm via ``TimeDistributed``)
and ``LogSoftMax`` is computed on the last axis of the unfolded
``(B, T, V)`` logits.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Linear, LogSoftMax, LookupTable, LSTM, Recurrent, RnnCell, Sequential,
    TimeDistributed,
)


def PTBModel(input_size: int, hidden_size: int = 200, output_size: int = None,
             num_layers: int = 2, key_type: str = "lstm") -> Sequential:
    """``input_size``/``output_size`` = vocabulary size (1-based ids in,
    per-step class log-probs out)."""
    output_size = output_size or input_size
    model = Sequential()
    model.add(LookupTable(input_size, hidden_size))
    in_size = hidden_size
    for _ in range(num_layers):
        cell = (LSTM(in_size, hidden_size) if key_type == "lstm"
                else RnnCell(in_size, hidden_size))
        model.add(Recurrent().add(cell))
        in_size = hidden_size
    model.add(TimeDistributed(Linear(hidden_size, output_size)))
    model.add(LogSoftMax())  # last-axis log-softmax on (B, T, V)
    return model


def SimpleRNN(input_size: int, hidden_size: int = 200,
              output_size: int = None) -> Sequential:
    return PTBModel(input_size, hidden_size, output_size, num_layers=1,
                    key_type="rnn")
