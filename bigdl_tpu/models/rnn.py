"""PTB language model — the reference's ``models/rnn`` zoo member.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/rnn/PTBModel.scala``
— ``LookupTable`` → stacked LSTM ``Recurrent`` layers → per-timestep
``Linear`` → ``LogSoftMax``; trained with ``TimeDistributedCriterion(
ClassNLLCriterion)`` over next-word targets. ``SimpleRNN`` is the
``RnnCell``-based variant from the same directory.

TPU-native notes: each LSTM layer is one ``lax.scan``; the output projection
runs on the folded ``(B·T, H)`` matrix (one MXU gemm via ``TimeDistributed``)
and ``LogSoftMax`` is computed on the last axis of the unfolded
``(B, T, V)`` logits.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Linear, LogSoftMax, LookupTable, LSTM, Recurrent, RnnCell, Sequential,
    TimeDistributed,
)


def PTBModel(input_size: int, hidden_size: int = 200, output_size: int = None,
             num_layers: int = 2, key_type: str = "lstm") -> Sequential:
    """``input_size``/``output_size`` = vocabulary size (1-based ids in,
    per-step class log-probs out)."""
    output_size = output_size or input_size
    model = Sequential()
    model.add(LookupTable(input_size, hidden_size))
    in_size = hidden_size
    for _ in range(num_layers):
        cell = (LSTM(in_size, hidden_size) if key_type == "lstm"
                else RnnCell(in_size, hidden_size))
        model.add(Recurrent().add(cell))
        in_size = hidden_size
    model.add(TimeDistributed(Linear(hidden_size, output_size)))
    model.add(LogSoftMax())  # last-axis log-softmax on (B, T, V)
    return model


def SimpleRNN(input_size: int, hidden_size: int = 200,
              output_size: int = None) -> Sequential:
    return PTBModel(input_size, hidden_size, output_size, num_layers=1,
                    key_type="rnn")


def train_main(argv=None):
    """Reference ``models/rnn/Train.scala`` main (PTB language model):
    ``-f`` = a text file (PTB ``train.txt`` style); synthetic markov-ish
    corpus otherwise."""
    import numpy as np

    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import (
        Dictionary, SequenceWindower, simple_tokenize,
    )
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import TimeDistributedCriterion, ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import Adagrad

    p = train_parser("PTB-style language model", batch_size=32,
                     learning_rate=0.1, max_epoch=2)
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--seqLen", type=int, default=20)
    p.add_argument("--hidden", type=int, default=128)
    args = p.parse_args(argv)

    rng = np.random.default_rng(0)
    samples = []
    vocab = args.vocab
    if args.folder:
        # real corpus: tokenize → id stream → next-word windows
        with open(args.folder) as f:
            tokens = simple_tokenize(f.read())
        d = Dictionary([tokens])
        vocab = d.vocab_size()
        ids = [d.get_index(t) + 1 for t in tokens]  # 1-based ids
        for ls in SequenceWindower(args.seqLen)(iter([ids])):
            samples.append(Sample(np.asarray(ls.data, np.float32),
                                  np.asarray(ls.labels, np.float32)))
        if not samples:
            raise ValueError(f"{args.folder}: corpus shorter than --seqLen")
    else:
        for _ in range(args.synthetic):
            # markov-ish synthetic ids: next token near the previous one
            toks = [int(rng.integers(1, vocab + 1))]
            for _ in range(args.seqLen):
                toks.append(1 + (toks[-1] + int(rng.integers(0, 3))) % vocab)
            arr = np.asarray(toks, np.float32)
            samples.append(Sample(arr[:-1], arr[1:]))  # predict next token
    model = PTBModel(vocab, hidden_size=args.hidden,
                     output_size=vocab, num_layers=1)
    crit = TimeDistributedCriterion(ClassNLLCriterion())
    return run_training(model, samples, crit, args,
                        optim_method=Adagrad(learning_rate=args.learningRate))


if __name__ == "__main__":
    train_main()
